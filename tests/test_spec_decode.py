"""Speculative decoding: draft-k-verify inside the compiled chunk loop.

The contract under test (``gpt.decode_steps_spec`` + the engine's
``spec_k`` step variant + the scheduler's payoff gate): speculation is
a pure PERF knob — verification is token-matching against the target's
own draws at the plain path's key fold points, so emitted streams are
bit-identical to the plain engine (and to solo ``gpt.generate``) for
greedy AND sampled requests, across tp shardings, quantized KV caches,
fault replay, and any gate flip pattern. Drafts only decide how many
tokens each wave yields.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import mesh as mx
from apex_tpu.kernels.decode_attention import (
    cache_write_columns,
    cache_write_columns_xla,
)
from apex_tpu.models import gpt
from apex_tpu.serving import Request, SamplingParams
from apex_tpu.serving.engine import Engine, EngineConfig
from apex_tpu.serving.resilience import FaultPlan, FaultSpec, ResilienceConfig
from apex_tpu.serving.scheduler import (
    GATE_CLOSED,
    GATE_OPEN,
    Scheduler,
    SpecGateConfig,
    _SpecGate,
)
from apex_tpu.transformer.testing import standalone_gpt_config

VOCAB = 96


def _cfg(**overrides):
    base = dict(vocab_size=VOCAB, seq_len=96)
    base.update(overrides)
    return standalone_gpt_config(**base)


def _solo_generate(cfg, params, mesh, prompt, n_new, sp: SamplingParams,
                   eos_token_id=None):
    pspecs = gpt.param_specs(cfg)
    key = (jax.random.PRNGKey(sp.seed)
           if sp.temperature > 0 and sp.seed is not None else None)
    out = jax.jit(jax.shard_map(
        lambda p, t: gpt.generate(
            cfg, p, t, n_new, temperature=sp.temperature, top_k=sp.top_k,
            top_p=sp.top_p, key=key, eos_token_id=eos_token_id,
            pad_token_id=0),
        mesh=mesh, in_specs=(pspecs, P(None, None)),
        out_specs=P(None, None), check_vma=False))(
            params, jnp.asarray([prompt], jnp.int32))
    return [int(t) for t in np.asarray(out)[0]]


def _requests(n, max_prompt_len, *, sampled_every=3, max_tokens=10):
    reqs = []
    for i in range(n):
        p_len = 1 + (7 * i + 3) % max_prompt_len
        prompt = [int(t) for t in jax.random.randint(
            jax.random.PRNGKey(500 + i), (p_len,), 0, VOCAB)]
        sp = (SamplingParams(temperature=0.9, top_k=7, seed=17 + i)
              if i % sampled_every == 1 else SamplingParams())
        reqs.append(Request(f"r{i}", prompt, max_tokens=max_tokens,
                            sampling=sp))
    return reqs


def _run(engine, reqs, **kw):
    sched = Scheduler(engine, **kw)
    for r in reqs:
        sched.submit(r)
    sched.run_until_idle()
    return sched


# --- drafter + kernel write units -------------------------------------------


def test_ngram_drafter_replays_cycles():
    """The device-side drafter replays a remembered cycle: with history
    ``... a b c a b`` and current token ``c``, the 2-gram match must
    draft ``a b c a ...``; an empty history falls back to repeating the
    current token; sentinels never match."""
    hist = jnp.asarray([
        [-1, -1, 7, 8, 9, 7, 8],     # cycle (7 8 9), current 9
        [-1, -1, -1, -1, -1, -1, -1],  # no history
    ], jnp.int32)
    tok = jnp.asarray([9, 5], jnp.int32)
    drafts = np.asarray(gpt.ngram_drafts(hist, tok, 4))
    assert drafts[0].tolist() == [7, 8, 9, 7]
    assert drafts[1].tolist() == [5, 5, 5, 5]


def test_cache_write_columns_kernel_matches_xla():
    """The Pallas multi-column write (interpret mode off-TPU) lands the
    same bytes as the XLA one-hot reference for in-horizon lanes; lanes
    clamped at the horizon only ever touch the last column."""
    rng = np.random.RandomState(0)
    b, h, s, d, t = 3, 2, 16, 8, 3
    k_cache = rng.randn(b, h, s, d).astype(np.float32)
    v_cache = rng.randn(b, h, s, d).astype(np.float32)
    k_new = rng.randn(b, h, t, d).astype(np.float32)
    v_new = rng.randn(b, h, t, d).astype(np.float32)
    pos = np.asarray([0, 5, 13], np.int32)  # row 2 overruns at lane 2
    kk, vk = cache_write_columns(
        jnp.asarray(k_new), jnp.asarray(v_new), jnp.asarray(k_cache),
        jnp.asarray(v_cache), jnp.asarray(pos))
    kx = cache_write_columns_xla(jnp.asarray(k_cache),
                                    jnp.asarray(k_new), jnp.asarray(pos))
    vx = cache_write_columns_xla(jnp.asarray(v_cache),
                                    jnp.asarray(v_new), jnp.asarray(pos))
    kk, vk, kx, vx = map(np.asarray, (kk, vk, kx, vx))
    for r in range(b):
        last_real = min(pos[r] + t, s) - (0 if pos[r] + t <= s else 1)
        np.testing.assert_array_equal(kk[r, :, :last_real],
                                      kx[r, :, :last_real])
        np.testing.assert_array_equal(vk[r, :, :last_real],
                                      vx[r, :, :last_real])
    # the clamped row: only column s-1 may differ from the XLA drop
    assert (kk[2, :, :s - 1] == kx[2, :, :s - 1]).all()
    # scale-plane (rank 3) spelling of the XLA write
    sc = rng.randn(b, h, s).astype(np.float32)
    new_sc = rng.randn(b, h, t).astype(np.float32)
    out = np.asarray(cache_write_columns_xla(
        jnp.asarray(sc), jnp.asarray(new_sc), jnp.asarray(pos)))
    for r in range(b):
        for j in range(t):
            if pos[r] + j < s:
                np.testing.assert_array_equal(out[r, :, pos[r] + j],
                                              new_sc[r, :, j])


# --- bit-parity oracles ------------------------------------------------------


def test_spec_greedy_and_sampled_match_solo_generate(devices8):
    """THE spec oracle: a spec_k engine's completions (greedy and
    seeded-sampled lanes) are token-identical to solo ``gpt.generate``
    — accept-prefix under token-matching verification reproduces the
    plain stream exactly. (Rerun determinism is pinned by the
    replay-after-fault test, which compares two independent runs.)"""
    cfg = _cfg()
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    eng = Engine(cfg, params, mesh, EngineConfig(
        slots=2, max_prompt_len=10, max_seq_len=32, decode_chunk=2,
        spec_k=3, spec_hist=12)).warmup()  # apex: noqa[TIER1-COST]: tiny spec engine; both step variants must pre-warm for the solo oracle
    reqs = _requests(4, 10)
    sched = _run(eng, reqs)
    eng.close()
    for r in reqs:
        comp = sched.completions[r.request_id]
        solo = _solo_generate(cfg, params, mesh, list(r.prompt),
                              r.max_tokens, r.sampling)
        assert comp.tokens == solo, (
            f"{r.request_id}: spec {comp.tokens} != solo {solo}")


def test_spec_logprobs_and_stop_sequences(devices8):
    """Spec streams carry per-token logprobs (free from the verify
    forward, ulp-equal to the plain path's), and stop sequences see the
    accepted prefix only — a stop match mid-wave trims exactly like the
    plain path (the pad lanes past the accepted prefix are not
    tokens)."""
    cfg = _cfg()
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    prompt = [int(t) for t in jax.random.randint(
        jax.random.PRNGKey(3), (6,), 0, VOCAB)]
    # sampled stream (greedy collapses to a constant): the stop pair is
    # two consecutive mid-stream tokens, so the match lands mid-wave
    sp = SamplingParams(temperature=0.9, top_k=7, seed=23)
    base = _solo_generate(cfg, params, mesh, prompt, 10, sp)
    stop = [base[4], base[5]]

    # independent reference: base fed through a fresh StopMatcher
    from apex_tpu.serving.request import StopMatcher
    ref = StopMatcher([stop])
    want = []
    for t in base:
        flushed, matched = ref.push(t)
        want += [tok for tok, _ in flushed]
        if matched:
            break

    def run_k(spec_k):
        with Engine(cfg, params, mesh, EngineConfig(
                slots=1, max_prompt_len=8, max_seq_len=32, decode_chunk=2,
                spec_k=spec_k)).warmup() as eng:  # apex: noqa[TIER1-COST]: per-k helper on the tiny spec engine; warm-cache warmup is seconds
            sched = _run(eng, [Request("s", prompt, max_tokens=10,
                                       sampling=sp, stop=[stop])])
            return sched.completions["s"]

    spec, plain = run_k(3), run_k(0)
    assert spec.finish_reason == plain.finish_reason == "stop"
    assert spec.tokens == plain.tokens == want  # trimmed emission
    assert len(spec.logprobs) == len(spec.tokens)
    np.testing.assert_allclose(spec.logprobs, plain.logprobs,
                               rtol=1e-5, atol=1e-6)


@pytest.mark.slow  # plain tp2 parity (test_serving) and solo spec parity stay tier-1; the composition is long-suite (fleet-router tier-1 offset)
def test_spec_tp2_matches_tp1(devices8):
    """Spec decode under tp=2 sharding emits the same streams as
    tp=1."""
    cfg = _cfg()
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    reqs = _requests(3, 8, max_tokens=8)

    def run_tp(tp):
        mesh = mx.build_mesh(tp=tp, devices=devices8[:tp])
        with Engine(cfg, params, mesh, EngineConfig(
                slots=2, max_prompt_len=8, max_seq_len=24, decode_chunk=2,
                spec_k=2)).warmup() as eng:  # apex: noqa[TIER1-COST]: tp-parity helper; tiny spec engine
            sched = _run(eng, reqs)
            return {k: c.tokens for k, c in sched.completions.items()}

    assert run_tp(1) == run_tp(2)


@pytest.mark.slow  # int8-KV parity and solo spec parity each stay tier-1; the composition is long-suite (fleet-router tier-1 offset)
def test_spec_int8_kv_parity(devices8):
    """Under an int8 KV cache, spec and plain engines still emit
    bit-identical streams to each other: the verify forward quantizes
    through the same deterministic quantizer as the plain write, so
    both paths hold the same cache bytes."""
    cfg = _cfg(kv_cache_dtype="int8")
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    reqs = _requests(3, 8, max_tokens=8)

    def run_k(spec_k):
        with Engine(cfg, params, mesh, EngineConfig(
                slots=2, max_prompt_len=8, max_seq_len=24, decode_chunk=2,
                spec_k=spec_k)).warmup() as eng:  # apex: noqa[TIER1-COST]: int8-KV spec parity helper; tiny engine
            sched = _run(eng, reqs)
            return {k: c.tokens for k, c in sched.completions.items()}

    assert run_k(2) == run_k(0)


# --- resilience + trace stability -------------------------------------------


def test_spec_replay_after_fault_exact(devices8):
    """A fault mid-spec-run replays interrupted requests bit-exactly:
    the chaotic run's non-error completions equal a fault-free run's
    (replay is forced onto the plain path while re-deriving, which must
    not change a single token)."""
    cfg = _cfg()
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    reqs = _requests(4, 8, max_tokens=10)

    def run_plan(plan):
        with Engine(cfg, params, mesh, EngineConfig(
                slots=2, max_prompt_len=8, max_seq_len=32, decode_chunk=2,
                spec_k=3), fault_plan=plan).warmup() as eng:  # apex: noqa[TIER1-COST]: fault-replay helper; warmed engine keeps replay exact
            sched = _run(eng, reqs, resilience=ResilienceConfig(
                backoff_base_s=0.001))
            return sched

    chaotic = run_plan(FaultPlan([FaultSpec("fetch", 2, "error")]))
    clean = run_plan(None)
    assert set(chaotic.completions) == set(clean.completions)
    for rid, comp in chaotic.completions.items():
        if comp.finish_reason == "error":
            continue
        assert comp.tokens == clean.completions[rid].tokens, rid
    assert chaotic.summary()["rebuilds"] >= 1.0


@pytest.mark.slow
def test_spec_recompile_guard_flat_across_switching(devices8):
    """Gate-driven spec/plain switching (probe cadence forced to
    alternate), fault replay, and admission waves never recompile:
    every program cache stays at 1 after warmup, step_spec included.
    Slow-marked (tier-1 budget offset for the paged-cache oracles):
    the same switching-under-guard invariant runs in tier-1 on the
    PAGED engine (`test_paged_cache.test_paged_spec_stream_parity`,
    forced gate alternation included); this keeps the contiguous
    spelling covered in the long suite."""
    cfg = _cfg()
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    eng = Engine(cfg, params, mesh, EngineConfig(
        slots=2, max_prompt_len=8, max_seq_len=32, decode_chunk=2,
        spec_k=3)).warmup()
    reqs = _requests(6, 8, max_tokens=8)  # host jax draws pre-guard
    with eng.recompile_guard():
        sched = _run(eng, reqs,
                     spec_gate=SpecGateConfig(probe_every=1,
                                              min_probe_chunks=1))
    sizes = eng.compiled_cache_sizes()
    for name in ("init", "step", "step_spec", "retire", "admit"):
        assert sizes[name] in (1, None), (name, sizes)
    assert sched.summary()["spec_chunks"] >= 1.0
    eng.close()


# --- the payoff gate ---------------------------------------------------------


def test_spec_gate_open_close_probe_cycle():
    """The gate state machine under injected acceptance traces: it
    measures plain first, probes spec, stays open while the acceptance
    EWMA clears the measured break-even, closes when acceptance
    collapses, and re-probes on the configured cadence (reopening only
    with the hysteresis margin)."""
    g = _SpecGate(SpecGateConfig(ewma_alpha=0.5, margin=1.05,
                                 probe_every=3, min_probe_chunks=2),
                  spec_k=3)
    assert not g.want_spec()            # no plain baseline yet
    g.observe_plain(0.010)
    assert g.want_spec()                # measuring the spec side
    g.observe_spec(0.015, 4.0)          # high acceptance, cheap verify
    g.observe_spec(0.015, 4.0)
    assert g.state() == GATE_OPEN and g.want_spec()
    # acceptance collapses: 1 token/wave < break-even 1.5 → the EWMA
    # (alpha 0.5: 4.0 → 2.5 → 1.75 → 1.375) closes on the third sample
    g.observe_spec(0.015, 1.0)
    g.observe_spec(0.015, 1.0)
    g.observe_spec(0.015, 1.0)
    assert g.state() == GATE_CLOSED
    # closed: plain chunks until the probe cadence fires
    for i in range(2):
        g.observe_plain(0.010)
        assert not g.want_spec()
    g.observe_plain(0.010)
    assert g.want_spec()                # probe_every=3 reached
    # a probe at recovered acceptance must clear margin × break-even
    g.observe_spec(0.015, 4.0)
    g.observe_spec(0.015, 4.0)
    assert g.state() == GATE_OPEN


def test_spec_gate_serialized_probes_and_plain_refresh():
    """The two pipelining hazards of fetch-side gate bookkeeping:
    (a) until the gate has measured its way open, ``want_spec`` with a
    speculative chunk already in flight must say plain — otherwise a
    depth-d pipeline dispatches d consecutive probe chunks per cadence,
    paying d× the documented probe overhead on 0%-acceptance traces;
    (b) an OPEN gate must emit one plain chunk per ``probe_every`` spec
    chunks to re-measure ``wall_plain`` — a frozen short-context
    baseline inflates the break-even as sequences grow and flaps the
    gate closed on exactly the workloads speculation pays for."""
    g = _SpecGate(SpecGateConfig(ewma_alpha=0.5, margin=1.05,
                                 probe_every=3, min_probe_chunks=2),
                  spec_k=3)
    g.observe_plain(0.010)
    # (a) measuring phase: one probe at a time
    assert g.want_spec() and not g.want_spec(spec_inflight=1)
    g.observe_spec(0.015, 4.0)
    g.observe_spec(0.015, 4.0)
    assert g.state() == GATE_OPEN
    # open gate: pipelined spec dispatches are NOT serialized
    assert g.want_spec(spec_inflight=2)
    # (b) probe_every spec chunks without a plain sample → refresh
    g.observe_spec(0.015, 4.0)          # spec_since_plain hits 3
    assert not g.want_spec()
    g.observe_plain(0.010)              # baseline refreshed
    assert g.want_spec() and g.state() == GATE_OPEN
    # (a) closed gate: the cadence probe is serialized too
    for _ in range(3):
        g.observe_spec(0.015, 1.0)      # acceptance collapses → closed
    assert g.state() == GATE_CLOSED
    for _ in range(3):
        g.observe_plain(0.010)
    assert g.want_spec() and not g.want_spec(spec_inflight=1)


@pytest.mark.slow
def test_spec_gate_e2e_high_vs_adversarial(devices8):
    """End-to-end gate behaviour: a repetitive greedy trace holds the
    gate open with high draft acceptance; an adversarial
    high-temperature trace collapses acceptance and ends with the gate
    closed — with streams bit-identical to the plain engine either
    way. The scheduler runs on an INJECTED ticking clock, so the
    measured chunk walls (and with them the gate's break-even = 1.0)
    are deterministic — the terminal gate state depends only on
    acceptance, never on host load. Slow-marked (tier-1 budget offset
    for the paged-cache oracles): the gate's decision arithmetic is
    unit-pinned above and `bench.py --mode serve`'s spec A/B runs this
    exact high-vs-adversarial regime on every bench run."""
    cfg = _cfg()
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])

    def run_trace(spec_k, sampled):  # apex: noqa[TIER1-COST]: helper of a slow-marked test (the closure walk can't see the enclosing mark)
        reqs = []
        for i in range(3):
            prompt = [int(t) for t in jax.random.randint(
                jax.random.PRNGKey(50 + i), (4,), 0, VOCAB)]
            sp = (SamplingParams(temperature=1.5, seed=i) if sampled
                  else SamplingParams())
            reqs.append(Request(f"r{i}", prompt, max_tokens=16,
                                sampling=sp))
        eng = Engine(cfg, params, mesh, EngineConfig(
            slots=4, max_prompt_len=8, max_seq_len=32, decode_chunk=2,
            spec_k=spec_k)).warmup()
        tick = [0.0]

        def clock():
            tick[0] += 1e-3
            return tick[0]

        sched = _run(eng, reqs, clock=clock, sleep=lambda s: None,
                     spec_gate=(SpecGateConfig(probe_every=1000)
                                if spec_k else None))
        eng.close()
        return ({k: c.tokens for k, c in sched.completions.items()},
                sched.summary())

    hi_toks, hi = run_trace(3, sampled=False)
    hi_plain, _ = run_trace(0, sampled=False)
    assert hi_toks == hi_plain
    assert hi["spec_accept_rate"] > 0.5, hi
    # ~4 tokens/wave against the deterministic break-even of 1.0: open
    assert hi["spec_gate_state"] == GATE_OPEN, hi
    adv_toks, adv = run_trace(3, sampled=True)
    adv_plain, _ = run_trace(0, sampled=True)
    assert adv_toks == adv_plain
    assert adv["spec_accept_rate"] < 0.3, adv
    # 1 token/wave cannot clear the break-even: closed after probing
    assert adv["spec_gate_state"] == GATE_CLOSED, adv


@pytest.mark.slow  # constrained serialization is pinned tier-1 in test_serving/test_api and the gate units; the spec composition is long-suite (multi-tenant tier-1 offset)
def test_spec_constrained_requests_force_plain(devices8):
    """A schema-constrained request (decode_chunk == 1, per-token mask
    advance) must never ride a speculative chunk — the gate is forced
    to the plain variant while one is active."""

    class WhitelistConstraint:
        """Minimal Request.constraint protocol: always allows the
        full vocab, never completes (the decode runs to budget)."""

        done = False

        def reset(self):
            pass

        def allowed_tokens(self):
            return list(range(VOCAB))

        def advance(self, tok):
            pass

    cfg = _cfg()
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    eng = Engine(cfg, params, mesh, EngineConfig(
        slots=2, max_prompt_len=8, max_seq_len=24, decode_chunk=1,
        spec_k=2)).warmup()
    prompt = [int(t) for t in jax.random.randint(
        jax.random.PRNGKey(9), (4,), 0, VOCAB)]
    sched = _run(eng, [Request("c", prompt, max_tokens=6,
                               constraint=WhitelistConstraint())])
    assert sched.completions["c"].tokens  # decoded through plain chunks
    assert sched.summary()["spec_chunks"] == 0.0
    eng.close()


def test_spec_config_validation(devices8):
    cfg = _cfg()
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    with pytest.raises(ValueError, match="spec_k"):
        Engine(cfg, params, mesh, EngineConfig(
            slots=1, max_prompt_len=8, max_seq_len=16, spec_k=-1))
    with pytest.raises(ValueError, match="spec_hist"):
        Engine(cfg, params, mesh, EngineConfig(
            slots=1, max_prompt_len=8, max_seq_len=16, spec_k=2,
            spec_hist=1))
    eng = Engine(cfg, params, mesh, EngineConfig(
        slots=1, max_prompt_len=8, max_seq_len=16))
    with pytest.raises(ValueError, match="spec_k"):
        eng.step_async(spec=True)
    with pytest.raises(ValueError, match="spec_gate"):
        Scheduler(eng, spec_gate=SpecGateConfig())
    with pytest.raises(ValueError, match="spec_k"):
        gpt.decode_steps_spec(
            dataclasses.replace(cfg), None, None, {}, 1, spec_k=0)
