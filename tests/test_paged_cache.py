"""Paged KV cache + chunked prefill oracles.

Oracle pattern (SURVEY.md §4): paged == contiguous BIT-parity — the
page pool plus block tables must be invisible to everything but the
byte counts. Model-level logits parity (XLA fallback: gathered bytes +
the contiguous score expressions verbatim) across plain/int8/fp8 and
single-/multi-column writes; engine-level stream parity (greedy AND
sampled) across plain, quantized, tp2-vs-tp1, speculative, and
fault-replay paths; copy-on-write prefix hits bit-identical to the
PR-7 pooled-slot hits; chunked-prefill admission bit-identical to
monolithic; allocator backpressure completing everything; and
recompile-guard flatness over a mixed paged workload.

Engines are built once per shape through the shared helper and their
streams cached in ``_STREAMS`` so parity tests never re-run a side.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import mesh as mx
from apex_tpu.models import gpt
from apex_tpu.serving import Request, SamplingParams
from apex_tpu.serving.engine import Engine, EngineConfig
from apex_tpu.serving.pages import SINK, PageAllocator, PagesExhausted
from apex_tpu.serving.scheduler import Scheduler
from apex_tpu.transformer.testing import standalone_gpt_config

VOCAB = 96


def _cfg(**overrides):
    base = dict(vocab_size=VOCAB, seq_len=64)
    base.update(overrides)
    return standalone_gpt_config(**base)


# -- the page allocator (pure host) -----------------------------------------


def test_page_allocator_semantics():
    a = PageAllocator(num_pages=9, page_size=8)
    assert a.capacity == 8 and a.free_pages == 8
    p1 = a.alloc(3)
    assert len(p1) == 3 and SINK not in p1
    assert a.pages_in_use == 3
    # copy-on-write pin: one more holder on an allocated page
    a.share(p1[:1])
    assert a.shared_pages == 1
    # all-or-nothing: a too-large request leaves state untouched
    with pytest.raises(PagesExhausted) as ei:
        a.alloc(6)
    assert ei.value.requested == 6 and ei.value.free == 5
    assert a.free_pages == 5
    # free drops one pin; the shared page survives its first free
    a.free(p1)
    assert a.free_pages == 7 and a.pages_in_use == 1
    a.free(p1[:1])
    assert a.free_pages == 8 and a.shared_pages == 0
    with pytest.raises(ValueError):
        a.free(p1[:1])  # double free
    with pytest.raises(ValueError):
        a.share([SINK])  # the sink is never a holder
    # fragmentation: 2 pages hold 10 of 16 possible tokens
    p2 = a.alloc(2)
    a.used_tokens += 10
    assert a.fragmentation() == pytest.approx(1.0 - 10 / 16)
    a.free(p2)
    a.reset()
    assert a.free_pages == 8 and a.used_tokens == 0
    # determinism: same call sequence, same page ids (fault replay)
    b = PageAllocator(num_pages=9, page_size=8)
    assert b.alloc(3) == PageAllocator(num_pages=9, page_size=8).alloc(3)


# -- model-level logits parity (the XLA-fallback bit-exact oracle) ----------


@pytest.mark.parametrize("kind", [
    "auto", "int8",
    # fp8 rides the identical quantized read/write paths as int8 with
    # only the storage dtype swapped — the costliest variant (~18 s)
    # runs in the slow tier; int8 keeps the quantized arm in tier-1
    # (tier-1 budget offset for the fleet-router suite)
    pytest.param("fp8", marks=pytest.mark.slow)])
def test_paged_decode_logits_oracle(devices8, kind):
    """Paged ``decode_step``/``decode_verify`` (block table through a
    scrambled page pool) emit BIT-identical logits to the contiguous
    cache under the XLA path — the gathered bytes + verbatim score
    expressions contract — for every cache storage kind, across
    chained single-column decode and a multi-column verify write."""
    if kind == "fp8" and not hasattr(jnp, "float8_e4m3fn"):
        pytest.skip("jax build without float8_e4m3fn")
    cfg = dataclasses.replace(_cfg(seq_len=64), kv_cache_dtype=kind)
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    pspecs = gpt.param_specs(cfg)
    b, p_sz, mp, n_pages = 2, 8, 6, 16
    s = mp * p_sz
    rng = np.random.RandomState(1)
    table = jnp.asarray(rng.permutation(np.arange(1, n_pages))[
        np.arange(b * mp).reshape(b, mp)].astype(np.int32))
    tok = jnp.array([5, 9], jnp.int32)

    def run(p, tk, tbl):
        cc = gpt.init_cache(cfg, p, b, s)
        pc = gpt.init_cache(cfg, p, n_pages, p_sz)
        pos = jnp.zeros((b,), jnp.int32)
        t_c = t_p = tk
        outs_c, outs_p = [], []
        for _ in range(4):
            lg_c, cc = gpt.decode_step(cfg, p, cc, t_c, pos)
            lg_p, pc = gpt.decode_step(cfg, p, pc, t_p, pos, tbl)
            outs_c.append(lg_c)
            outs_p.append(lg_p)
            t_c = jnp.argmax(lg_c, -1).astype(jnp.int32)
            t_p = jnp.argmax(lg_p, -1).astype(jnp.int32)
            pos = pos + 1
        # the speculative verify's multi-column write + follow-on read
        toks = jnp.stack([t_c, (t_c + 1) % VOCAB, (t_c + 2) % VOCAB],
                         axis=1)
        la_c, cc = gpt.decode_verify(cfg, p, cc, toks, pos)
        la_p, pc = gpt.decode_verify(cfg, p, pc, toks, pos, tbl)
        lf_c, _ = gpt.decode_step(cfg, p, cc, t_c, pos + 3)
        lf_p, _ = gpt.decode_step(cfg, p, pc, t_p, pos + 3, tbl)
        return jnp.stack(outs_c), jnp.stack(outs_p), la_c, la_p, lf_c, lf_p

    oc, op, la_c, la_p, lf_c, lf_p = jax.jit(jax.shard_map(
        run, mesh=mesh, in_specs=(pspecs, P(None), P(None, None)),
        out_specs=P(*[None] * 3), check_vma=False))(params, tok, table)
    np.testing.assert_array_equal(np.asarray(oc), np.asarray(op))
    np.testing.assert_array_equal(np.asarray(la_c), np.asarray(la_p))
    np.testing.assert_array_equal(np.asarray(lf_c), np.asarray(lf_p))


def test_paged_kernel_vs_xla_oracle(devices8):
    """The Pallas paged kernels (interpreted off-TPU) agree with the
    XLA paged fallback within kernel-oracle tolerance, and greedily
    emit the same tokens — the on-chip read/write path's CPU oracle."""
    cfgs = {impl: dataclasses.replace(_cfg(seq_len=64),
                                      decode_attn_impl=impl)
            for impl in ("kernel", "xla")}
    params = gpt.init(cfgs["xla"], jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    pspecs = gpt.param_specs(cfgs["xla"])
    b, p_sz, mp, n_pages = 2, 8, 6, 14
    table = jnp.asarray(np.arange(1, 1 + b * mp, dtype=np.int32)
                        .reshape(b, mp))
    tok = jnp.array([5, 9], jnp.int32)

    def mk(c):
        def run(p, tk, tbl):
            pc = gpt.init_cache(c, p, n_pages, p_sz)
            pos = jnp.zeros((b,), jnp.int32)
            t = tk
            outs = []
            for _ in range(4):
                lg, pc = gpt.decode_step(c, p, pc, t, pos, tbl)
                outs.append(lg)
                t = jnp.argmax(lg, -1).astype(jnp.int32)
                pos = pos + 1
            return jnp.stack(outs)
        return jax.jit(jax.shard_map(
            run, mesh=mesh, in_specs=(pspecs, P(None), P(None, None)),
            out_specs=P(None, None, None), check_vma=False))

    ok = np.asarray(mk(cfgs["kernel"])(params, tok, table))
    ox = np.asarray(mk(cfgs["xla"])(params, tok, table))
    np.testing.assert_allclose(ok, ox, rtol=2e-4, atol=2e-4)
    np.testing.assert_array_equal(ok.argmax(-1), ox.argmax(-1))


# -- engine-level stream parity ---------------------------------------------

#: streams keyed by (shape, side) — parity tests read a side another
#: test already produced instead of re-running it
_STREAMS = {}


def _mk_engine(cfg, ecfg, mesh, fault_plan=None):  # apex: noqa[TIER1-COST]: shared tiny-engine builder — one warm-cache warmup per paged-parity variant serves every test below
    return Engine(cfg, params_of(cfg), mesh, ecfg,
                  fault_plan=fault_plan).warmup()


_PARAMS = {}


def params_of(cfg):
    # one shared init — parameters are storage-kind independent
    if "p" not in _PARAMS:
        base = dataclasses.replace(cfg, kv_cache_dtype="auto")
        _PARAMS["p"] = gpt.init(base, jax.random.PRNGKey(0))
    return _PARAMS["p"]


def _trace(n=6, mt=6, mpl=14, long_every=0, long_len=0, prefix=None):
    reqs = []
    for i in range(n):
        if long_every and i % long_every == 1:
            p_len = long_len
        else:
            p_len = 1 + (7 * i + 3) % mpl
        body = [int(t) for t in jax.random.randint(
            jax.random.PRNGKey(50 + i), (p_len,), 0, VOCAB)]
        prompt = (list(prefix) + body[:3]) if prefix and i % 2 == 0 \
            else body
        sp = (SamplingParams(temperature=0.9, top_k=20, seed=i)
              if i % 2 else SamplingParams())
        reqs.append(Request(f"r{i}", prompt, max_tokens=mt, sampling=sp))
    return reqs


def _run(engine, reqs, **kw):
    sched = Scheduler(engine, **kw)
    for r in reqs:
        sched.submit(r)
    sched.run_until_idle()
    return ({rid: c.tokens for rid, c in sched.completions.items()},
            sched.summary())


_ECFG = EngineConfig(slots=3, max_prompt_len=16, max_seq_len=32,
                     decode_chunk=2, prompt_buckets=(8, 16),
                     admit_batch_sizes=(1, 2))


def _baseline(devices8, kind="auto"):
    key = ("base", kind)
    if key not in _STREAMS:
        cfg = dataclasses.replace(_cfg(), kv_cache_dtype=kind)
        eng = _mk_engine(cfg, _ECFG,
                         mx.build_mesh(tp=1, devices=devices8[:1]))
        _STREAMS[key] = _run(eng, _trace())[0]
        eng.close()
    return _STREAMS[key]


@pytest.mark.parametrize("kind", [
    "auto",
    # the int8 engine-level stream parity is the logits oracle's int8
    # arm composed with the (auto-covered) engine plumbing — slow tier
    # (tier-1 budget offset for the fleet-router suite)
    pytest.param("int8", marks=pytest.mark.slow)])
def test_paged_engine_stream_parity(devices8, kind):
    """A paged engine emits BIT-identical token streams (greedy and
    sampled rows alike) to the contiguous engine — plain and
    quantized-KV storage; pages all return to the pool at drain."""
    base = _baseline(devices8, kind)
    cfg = dataclasses.replace(_cfg(), kv_cache_dtype=kind)
    eng = _mk_engine(cfg, dataclasses.replace(_ECFG, page_size=8),
                     mx.build_mesh(tp=1, devices=devices8[:1]))
    toks, s = _run(eng, _trace())
    _STREAMS[("paged", kind)] = toks
    eng.close()
    assert toks == base
    assert s["pages_in_use"] == 0.0  # every release freed its pages


@pytest.mark.slow  # plain tp2 parity (test_serving) stays tier-1; this paged-only composition is subsumed by the composed-path oracle below — both long-suite (self-tuning-runtime tier-1 offset)
def test_paged_tp2_vs_tp1_parity(devices8):
    """Paged decode under tp=2 (heads sharded; pool + tables
    replicated geometry) emits the tp=1 paged streams bit-for-bit."""
    base = _baseline(devices8, "auto")
    eng = _mk_engine(_cfg(), dataclasses.replace(_ECFG, page_size=8),
                     mx.build_mesh(tp=2, devices=devices8[:2]))
    toks, _ = _run(eng, _trace())
    eng.close()
    assert toks == base


@pytest.mark.slow
def test_composed_tp2_vs_tp1_full_path_parity(devices8):
    """THE full composed serving path the ROADMAP flagged as
    uncovered, tp2 vs tp1 in ONE run: pipelined decode (depth 2) +
    batched bucketed admission + prefix-pool hits mapped
    copy-on-write + the paged cache. Every per-feature tp oracle
    (plain, quantized, spec, paged) passes individually; this pins
    the COMPOSITION — sharded gathers over shared pages while chunks
    are in flight behind batched bucketed admissions — bit-identical
    across shardings."""
    cfg = _cfg()
    ecfg = dataclasses.replace(_POOL_ECFG, page_size=8)
    toks = {}
    for tp in (1, 2):
        eng = _mk_engine(cfg, ecfg,
                         mx.build_mesh(tp=tp, devices=devices8[:tp]))
        eng.register_prefix(_template())
        toks[tp], s = _run(eng, _prefix_trace(), pipeline_depth=2)
        eng.close()
        # the run must actually exercise every composed feature
        assert s["prefix_hits"] > 0 and s["page_share_hits"] > 0
        assert s["pipeline_depth"] == 2.0
        assert s["admitted_requests"] == 6.0
        assert s["pages_in_use"] == 16 / 8  # only registration pins
    assert toks[2] == toks[1]


def test_paged_spec_stream_parity(devices8):
    """Speculative decoding over the paged cache (draft-verify's
    multi-column paged writes included) stays bit-identical to the
    plain contiguous path, and the guard stays flat across the gate's
    spec/plain switching on paged tables (probe cadence forced to
    alternate — every program, table re-upload included, must hold
    cache size 1)."""
    from apex_tpu.serving.scheduler import SpecGateConfig

    base = _baseline(devices8, "auto")
    eng = _mk_engine(_cfg(), dataclasses.replace(
        _ECFG, page_size=8, spec_k=2),
        mx.build_mesh(tp=1, devices=devices8[:1]))
    with eng.recompile_guard():
        toks, s = _run(eng, _trace(), spec_gate=SpecGateConfig(
            probe_every=1, min_probe_chunks=1))
    sizes = {k: v for k, v in eng.compiled_cache_sizes().items()
             if v is not None}
    eng.close()
    assert toks == base
    assert all(v == 1 for v in sizes.values()), sizes


def test_paged_fault_replay_parity(devices8):
    """A mid-serve fault on the paged engine (donated buffers +
    tables + allocator rebuilt, prefix-free) replays interrupted
    requests to bit-identical completions — the paged layout is
    invisible to deterministic replay."""
    from apex_tpu.serving.resilience import FaultPlan, FaultSpec

    base = _baseline(devices8, "auto")
    plan = FaultPlan([FaultSpec(point="fetch", index=2, kind="error")])
    eng = _mk_engine(_cfg(), dataclasses.replace(_ECFG, page_size=8),
                     mx.build_mesh(tp=1, devices=devices8[:1]),
                     fault_plan=plan)
    toks, s = _run(eng, _trace())
    eng.close()
    assert s["rebuilds"] >= 1.0
    assert toks == base
    assert len(plan.injected) == 1


# -- copy-on-write prefix sharing + chunked prefill -------------------------

_POOL_ECFG = EngineConfig(slots=3, max_prompt_len=32, max_seq_len=48,
                          decode_chunk=2, prompt_buckets=(8, 16, 32),
                          admit_batch_sizes=(1, 2),
                          prefix_pool_slots=1)


def _template():
    return [int(t) for t in jax.random.randint(
        jax.random.PRNGKey(900), (16,), 0, VOCAB)]


def _prefix_trace():
    return _trace(n=6, mt=6, mpl=5, prefix=_template())


@pytest.mark.parametrize("kind", [
    "auto",
    # the quantized CoW pair rides the identical pagein/insert code
    # path (same quantizer, same inputs) — long-suite confirmation,
    # not tier-1 budget
    pytest.param("int8", marks=pytest.mark.slow),
])
def test_cow_prefix_hits_bit_identical(devices8, kind):
    """Paged prefix hits map the registered prefix's pages
    copy-on-write (zero prefix bytes moved at admission) and emit
    BIT-identical streams to the PR-7 pooled-slot hits; the shared
    pages survive every hit's release (refcount pin) so a second
    admission wave still shares them."""
    cfg = dataclasses.replace(_cfg(), kv_cache_dtype=kind)
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    eng_pool = _mk_engine(cfg, _POOL_ECFG, mesh)
    eng_pool.register_prefix(_template())
    pooled, _ = _run(eng_pool, _prefix_trace())
    eng_pool.close()
    eng_cow = _mk_engine(cfg, dataclasses.replace(
        _POOL_ECFG, page_size=8), mesh)
    eng_cow.register_prefix(_template())
    cow, s1 = _run(eng_cow, _prefix_trace())
    assert cow == pooled
    assert s1["page_share_hits"] == s1["prefix_hits"] > 0
    # second wave: the prefix pages are still pinned and still shared
    cow2, s2 = _run(eng_cow, _prefix_trace())
    assert cow2 == pooled
    assert s2["page_share_hits"] > 0
    # only the registration pins remain mapped after drain
    stats = eng_cow.page_stats()
    eng_cow.close()
    assert stats["pages_in_use"] == 16 / 8  # the pinned prefix pages
    assert stats["pages_shared"] == 0.0


def test_chunked_prefill_stream_parity(devices8):
    """Chunked-prefill admission (chunk-0 cold prefill +
    ``prefill_extend`` chunks + finish, decode waves interleaved at
    chunk boundaries) emits BIT-identical streams to monolithic
    admission — on the paged cache, under a flat recompile guard,
    with every compiled program used exactly once."""
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    trace_kw = dict(n=6, mt=6, mpl=14, long_every=3, long_len=30)
    eng_m = _mk_engine(_cfg(), dataclasses.replace(
        _POOL_ECFG, prefix_pool_slots=0), mesh)
    base, _ = _run(eng_m, _trace(**trace_kw))
    eng_m.close()
    eng_ch = _mk_engine(_cfg(), dataclasses.replace(
        _POOL_ECFG, prefix_pool_slots=0, page_size=8,
        prefill_chunk=16), mesh)
    with eng_ch.recompile_guard():
        toks, s = _run(eng_ch, _trace(**trace_kw))
    sizes = {k: v for k, v in eng_ch.compiled_cache_sizes().items()
             if v is not None}
    eng_ch.close()
    assert toks == base
    assert s["chunked_admissions"] == 2.0  # the two 30-token prompts
    assert s["chunked_chunks"] == 4.0      # two chunks each
    assert all(v == 1 for v in sizes.values()), sizes


def test_paged_backpressure_completes_everything(devices8):
    """An oversubscribed pool (fewer pages than the burst needs at
    once) backpressures admissions instead of failing them: every
    request still completes with bit-identical streams, pages_exhausted
    waits are observed, and the pool drains back to empty."""
    base = _baseline(devices8, "auto")
    eng = _mk_engine(_cfg(), dataclasses.replace(
        _ECFG, page_size=8, num_pages=8),  # 7 allocatable ≈ 2 slots
        mx.build_mesh(tp=1, devices=devices8[:1]))
    toks, s = _run(eng, _trace())
    eng.close()
    assert toks == base
    assert s["pages_exhausted_waits"] > 0
    assert s["pages_in_use"] == 0.0
