"""Exhaustive SURVEY.md §2 symbol audit: every key symbol family the
survey names must resolve at its apex-canonical apex_tpu location
(aliases allowed, capability must exist). Complements the behavioural
checks in test_symbol_parity.py."""

import importlib


CHECKS = {
    # §2.1 amp
    "apex_tpu.amp": [
        "initialize", "scale_loss", "master_params", "state_dict",
        "load_state_dict", "Policy", "get_policy", "ScalerConfig",
        "ScalerState", "all_finite", "apply_if_finite", "unscale",
        "value_and_scaled_grad", "update_scale_hysteresis"],
    # §2.1 fp16_utils
    "apex_tpu.fp16_utils": [
        "network_to_half", "BN_convert_float", "FP16Model",
        "prep_param_lists", "master_params_to_model_params",
        "model_grads_to_master_grads", "FP16_Optimizer", "LossScaler",
        "DynamicLossScaler"],
    # §2.1 multi_tensor_apply
    "apex_tpu.multi_tensor": [
        "MultiTensorApply", "pack", "unpack", "flatten_dense_tensors",
        "unflatten_dense_tensors"],
    # §2.1 optimizers
    "apex_tpu.optimizers": [
        "FusedAdam", "FusedLAMB", "FusedSGD", "FusedNovoGrad",
        "FusedAdagrad", "FusedMixedPrecisionLamb", "DistributedFusedAdam",
        "DistributedFusedLAMB", "larc_transform"],
    # §2.1 normalization
    "apex_tpu.normalization": [
        "FusedLayerNorm", "MixedFusedLayerNorm", "FusedRMSNorm",
        "MixedFusedRMSNorm"],
    # §2.1 parallel
    "apex_tpu.parallel": [
        "DistributedDataParallel", "Reducer", "flat_dist_call",
        "SyncBatchNorm", "convert_syncbn_model", "LARC",
        "initialize_distributed"],
    # §2.1 mlp/fused_dense/rnn/reparam
    "apex_tpu.mlp": ["MLP", "mlp"],
    "apex_tpu.fused_dense": ["FusedDense", "FusedDenseGeluDense"],
    "apex_tpu.rnn": None,  # module presence
    "apex_tpu.reparameterization": None,
    # §2.2 transformer
    "apex_tpu.transformer.parallel_state": [
        "initialize_model_parallel", "get_tensor_model_parallel_group",
        "get_tensor_model_parallel_rank",
        "get_tensor_model_parallel_world_size",
        "get_pipeline_model_parallel_rank", "get_data_parallel_world_size",
        "is_pipeline_first_stage", "is_pipeline_last_stage",
        "destroy_model_parallel"],
    "apex_tpu.transformer.tensor_parallel.mappings": [
        "copy_to_tensor_model_parallel_region",
        "reduce_from_tensor_model_parallel_region",
        "scatter_to_tensor_model_parallel_region",
        "gather_from_tensor_model_parallel_region",
        "scatter_to_sequence_parallel_region",
        "gather_from_sequence_parallel_region",
        "reduce_scatter_to_sequence_parallel_region"],
    "apex_tpu.transformer.tensor_parallel": [
        "ColumnParallelLinear", "RowParallelLinear", "VocabParallelEmbedding",
        "column_parallel_linear", "row_parallel_linear",
        "vocab_parallel_embedding", "vocab_parallel_cross_entropy",
        "checkpoint", "get_cuda_rng_tracker",
        "set_tensor_model_parallel_attributes", "broadcast_data",
        "VocabUtility", "divide", "split_tensor_along_last_dim"],
    "apex_tpu.transformer.pipeline_parallel": [
        "get_forward_backward_func", "forward_backward_no_pipelining",
        "forward_backward_pipelining_without_interleaving",
        "forward_backward_pipelining_with_interleaving"],
    "apex_tpu.transformer.microbatches": [
        "setup_microbatch_calculator", "build_num_microbatches_calculator",
        "ConstantNumMicroBatches", "RampupBatchsizeNumMicroBatches"],
    "apex_tpu.transformer.functional": [
        "FusedScaleMaskSoftmax", "ScaledMaskedSoftmax",
        "ScaledUpperTriangMaskedSoftmax", "GenericScaledMaskedSoftmax"],
    "apex_tpu.transformer.enums": ["AttnMaskType", "ModelType", "LayerType"],
    "apex_tpu.transformer.log_util": [
        "set_logging_level", "get_transformer_logger"],
    "apex_tpu.testing": None,
    # §2.3 kernels (TPU-native equivalents)
    "apex_tpu.kernels": [
        "flash_attention", "layer_norm", "rms_norm",
        "scaled_masked_softmax", "scaled_upper_triang_masked_softmax",
        "generic_scaled_masked_softmax",
        "softmax_cross_entropy"],
    "apex_tpu.kernels.flat_ops": [
        "scale_flat", "axpby_flat", "l2norm_flat", "adam_flat", "sgd_flat",
        "adagrad_flat"],
    # §2.4 contrib
    "apex_tpu.contrib": [
        "clip_grad_norm_", "sigmoid_focal_loss", "index_mul_2d",
        "group_norm_nhwc", "group_batch_norm_nhwc"],
    "apex_tpu.contrib.multihead_attn": [
        "SelfMultiheadAttn", "EncdecMultiheadAttn"],
    "apex_tpu.contrib.sparsity": None,
    "apex_tpu.contrib.transducer": None,
    "apex_tpu.contrib.bottleneck": None,
    "apex_tpu.contrib.spatial": None,
    "apex_tpu.contrib.conv_bias_relu": None,
    # distributed / ZeRO
    "apex_tpu.optimizers.distributed": [
        "distributed_fused_adam", "distributed_fused_lamb"],
    # aux subsystems
    "apex_tpu.profiler": None,
    "apex_tpu.checkpoint": None,
    "apex_tpu.data": None,
    "apex_tpu.mesh": ["build_mesh", "build_hybrid_mesh"],
    "apex_tpu.transformer.context_parallel": [
        "ring_attention", "ulysses_attention"],
    "apex_tpu.models.gpt": [
        "GPTConfig", "init", "loss", "logits", "generate", "decode_step",
        "init_cache", "param_specs", "pipeline_loss"],
    "apex_tpu.transformer.moe": [
        "MoEConfig", "init_moe", "moe_ffn", "moe_pspecs"],
    # §2.2 misc transformer: LN wrapper + testing helpers at canonical paths
    "apex_tpu.transformer.layers": [
        "FastLayerNorm", "FusedLayerNorm", "get_layer_norm"],
    "apex_tpu.transformer.testing": [
        "request_cpu_devices", "assert_devices",
        "standalone_gpt_config", "standalone_bert_config"],
}



def test_survey_symbol_audit():
    missing = []
    for mod, syms in CHECKS.items():
        try:
            m = importlib.import_module(mod)
        except Exception as e:  # pragma: no cover - report below
            missing.append((mod, f"IMPORT FAIL {e}"))
            continue
        for s in (syms or []):
            if not hasattr(m, s):
                missing.append((mod, s))
    assert not missing, missing
