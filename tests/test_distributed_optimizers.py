"""ZeRO-sharded optimizers vs their replicated references.

Parity model: apex/contrib/test/ distributed Adam/LAMB tests (U) — the
sharded optimizer must produce the same updated params as the unsharded
one given identical gradients, while holding only 1/dp of the moments.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import mesh as mx
from apex_tpu import multi_tensor as mt
from apex_tpu.amp import ScalerConfig
from apex_tpu.models import gpt, training
from apex_tpu.optimizers import (
    distributed_fused_adam,
    distributed_fused_lamb,
    fused_adam,
    fused_lamb,
)


def _tree(key):
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "a": jax.random.normal(k1, (37, 5)),
        "b": jax.random.normal(k2, (130,)),
        "c": {"w": jax.random.normal(k3, (8, 8, 3))},
    }


def smap(f, mesh, in_specs, out_specs):
    return jax.jit(jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False))


def _run_steps(opt, dist, mesh, params, grads, n=3):
    """Run n identical-gradient steps; return final params from each."""

    def ref_fn(p, g):
        st = opt.init(p)
        for _ in range(n):
            p, st = opt.step(g, st, p)
        return p

    def dist_fn(p, g):
        st = dist.init(p)
        for _ in range(n):
            p, st = dist.step(g, st, p)
        return p

    specs = jax.tree.map(lambda _: P(), params)
    ref = smap(ref_fn, mesh, (specs, specs), specs)(params, grads)
    out = smap(dist_fn, mesh, (specs, specs), specs)(params, grads)
    return jax.device_get(ref), jax.device_get(out)


def test_distributed_adam_matches_fused_adam(devices8):
    mesh = mx.build_mesh(tp=1, devices=devices8[:4])  # dp=4
    params = _tree(jax.random.PRNGKey(0))
    grads = _tree(jax.random.PRNGKey(1))
    ref, out = _run_steps(
        fused_adam(1e-2, weight_decay=0.01),
        distributed_fused_adam(1e-2, weight_decay=0.01),
        mesh, params, grads)
    for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(out)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)


@pytest.mark.parametrize("grad_averaging", [True, False])
def test_distributed_lamb_matches_fused_lamb(devices8, grad_averaging):
    """ZeRO LAMB == unsharded LAMB, with and without grad averaging (the
    latter pins the kwarg threading into the sharded adam sweep)."""
    mesh = mx.build_mesh(tp=1, devices=devices8[:4])
    params = _tree(jax.random.PRNGKey(2))
    grads = _tree(jax.random.PRNGKey(3))
    ref, out = _run_steps(
        fused_lamb(1e-2, grad_averaging=grad_averaging),
        distributed_fused_lamb(1e-2, grad_averaging=grad_averaging),
        mesh, params, grads)
    for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(out)):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


def test_sharded_state_is_one_over_dp(devices8):
    mesh = mx.build_mesh(tp=1, devices=devices8)  # dp=8
    params = _tree(jax.random.PRNGKey(0))
    dist = distributed_fused_adam(1e-3)
    _, layout = mt.pack(params)
    st_shapes = jax.eval_shape(lambda p: dist.init(p, dp=8), params)
    for m, full in zip(st_shapes.m, layout.group_sizes):
        # shards are padded to the full pack quantum (fast kernel blocks)
        assert m.shape[0] == mt.pad_to((full + 7) // 8)
        assert m.shape[0] % 128 == 0
    # the ZeRO memory claim — shard ≈ full/dp — at real model sizes, where
    # the quantum is noise (355M params, dp=8)
    big = mt.pad_to(355_000_000)
    shard = mt.pad_to((big + 7) // 8)
    assert shard < big // 8 + 2 * mt.pad_to(1)


def test_zero_train_step_end_to_end(devices8):
    """GPT + ZeRO Adam over tp=2 x dp=4: loss decreases, scaler engaged."""
    cfg = gpt.GPTConfig(vocab_size=96, hidden_size=64, num_layers=2,
                        num_heads=4, seq_len=32, compute_dtype=jnp.float32)
    mesh = mx.build_mesh(tp=2, devices=devices8)
    init_fn, step_fn = training.make_train_step(
        cfg, mesh, distributed_fused_adam(1e-2),
        ScalerConfig(enabled=False))
    state = init_fn(jax.random.PRNGKey(0))
    tok = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 96)
    tgt = jnp.roll(tok, -1, 1)
    losses = []
    for _ in range(5):
        state, m = step_fn(state, tok, tgt)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    assert all(np.isfinite(losses))
