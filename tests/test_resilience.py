"""apex_tpu.serving.resilience — chaos suite.

Headline oracle: a fault injected at ANY engine seam (admit /
dispatch / fetch, plus NaN batches, hangs, and queue floods) never
kills the engine — the failing chunk is quarantined, buffers rebuild,
interrupted requests replay deterministically, and every request
untouched by the fault (plus every successfully retried one) completes
with tokens bit-identical to its solo ``gpt.generate`` run. Health
transitions are observed end-to-end through a LIVE ``/healthz`` scrape,
and the registry counters reconcile against the injected plan."""

import json
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import checkpoint as ckpt
from apex_tpu import mesh as mx
from apex_tpu.models import gpt
from apex_tpu.serving import Request, SamplingParams
from apex_tpu.serving.engine import Engine, EngineConfig
from apex_tpu.serving.request import (
    FINISH_ERROR,
    FINISH_LENGTH,
    FINISH_TIMEOUT,
)
from apex_tpu.serving.resilience import (
    EngineFailed,
    EngineFault,
    FaultPlan,
    FaultSpec,
    HealthMonitor,
    ResilienceConfig,
    parse_fault_plan,
)
from apex_tpu.serving.scheduler import QueueFull, Scheduler
from apex_tpu.telemetry import MetricsServer, Registry, parse_prometheus_text
from apex_tpu.transformer.testing import standalone_gpt_config

VOCAB = 96


def _cfg(**overrides):
    base = dict(vocab_size=VOCAB, seq_len=64)
    base.update(overrides)
    return standalone_gpt_config(**base)


@pytest.fixture(scope="module")
def model(devices8):
    cfg = _cfg()
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    return cfg, params, mesh


def _solo_generate(cfg, params, mesh, prompt, n_new, sp: SamplingParams,
                   eos_token_id=None):
    """The parity reference: one ``gpt.generate`` run with this
    request's params and key."""
    import jax.numpy as jnp

    pspecs = gpt.param_specs(cfg)
    key = (jax.random.PRNGKey(sp.seed)
           if sp.temperature > 0 and sp.seed is not None else None)
    out = jax.jit(jax.shard_map(
        lambda p, t: gpt.generate(
            cfg, p, t, n_new, temperature=sp.temperature, top_k=sp.top_k,
            top_p=sp.top_p, key=key, eos_token_id=eos_token_id,
            pad_token_id=0),
        mesh=mesh, in_specs=(pspecs, P(None, None)),
        out_specs=P(None, None), check_vma=False))(
            params, jnp.asarray([prompt], jnp.int32))
    return [int(t) for t in np.asarray(out)[0]]


def _reqs(n, *, seed0=7000, max_tokens=6):
    """Deterministic mixed trace: greedy + seeded-sampled lanes (every
    scheduler-visible request is deterministic, which is exactly what
    makes replay-after-rebuild bit-identical)."""
    out = []
    for i in range(n):
        p_len = 2 + (3 * i) % 6
        prompt = [int(t) for t in jax.random.randint(
            jax.random.PRNGKey(seed0 + i), (p_len,), 0, VOCAB)]
        sp = (SamplingParams(temperature=0.9, top_k=7, seed=seed0 + i)
              if i % 2 else SamplingParams())
        out.append(Request(f"c{seed0}_{i}", prompt, max_tokens=max_tokens,
                           sampling=sp))
    return out


def _assert_parity(cfg, params, mesh, sched, reqs, *, skip=()):
    for r in reqs:
        if r.request_id in skip:
            continue
        comp = sched.completions[r.request_id]
        want = _solo_generate(cfg, params, mesh, list(r.prompt),
                              r.max_tokens, r.sampling, r.eos_token_id)
        assert comp.tokens == want, (
            f"{r.request_id}: engine {comp.tokens} != solo {want}")


def _mk_engine(cfg, params, mesh, plan=None, *, slots=2, chunk=2,
               mpl=8, msl=24):
    return Engine(cfg, params, mesh,
                  EngineConfig(slots=slots, max_prompt_len=mpl,
                               max_seq_len=msl, decode_chunk=chunk),
                  fault_plan=plan)


# --- plan + health unit coverage (host-only, fast) --------------------------


def test_fault_plan_deterministic_and_validated():
    plan = FaultPlan([FaultSpec("fetch", 1, "nan", slots=(1,)),
                      FaultSpec("admit", 0, "error")])
    assert plan.take("fetch") is None          # call 0: clean
    spec = plan.take("fetch")                  # call 1: the fault
    assert spec is not None and spec.kind == "nan"
    assert plan.take("fetch") is None
    assert plan.injected == [spec]
    assert plan.counts()["fetch"] == 3
    plan.reset()
    assert plan.injected == [] and plan.counts()["fetch"] == 0
    # seeded plans are exact reruns
    assert FaultPlan.random(11, 5).specs == FaultPlan.random(11, 5).specs
    assert FaultPlan.random(11, 5).specs != FaultPlan.random(12, 5).specs
    # CLI parsing round trip
    p = parse_fault_plan("fetch:2:nan:1,dispatch:5:error,fetch:7:hang:0.5")
    kinds = {(s.point, s.index): s for s in p.specs}
    assert kinds[("fetch", 2)].slots == (1,)
    assert kinds[("fetch", 7)].hang_s == 0.5
    assert parse_fault_plan("random:3:4").specs == \
        FaultPlan.random(3, 4).specs
    with pytest.raises(ValueError, match="unknown fault point"):
        FaultPlan([FaultSpec("teleport", 0, "error")])
    with pytest.raises(ValueError, match="not injectable"):
        FaultPlan([FaultSpec("dispatch", 0, "nan")])
    with pytest.raises(ValueError, match="duplicate"):
        FaultPlan([FaultSpec("fetch", 1, "error"),
                   FaultSpec("fetch", 1, "hang")])


def test_health_monitor_machine_and_gauge():
    reg = Registry()
    h = HealthMonitor(registry=reg, recovery_chunks=2)
    gauge = reg.gauge("serving_health_state")
    assert h.state == "ok" and h.healthz() == (200, "ok\n")
    h.record_fault("watchdog")
    assert h.state == "degraded" and gauge.value == 1.0
    code, body = h.healthz()
    assert code == 200 and body.startswith("degraded")
    h.record_progress()
    assert h.state == "degraded"  # one healthy chunk is not enough
    h.record_progress()
    assert h.state == "ok" and gauge.value == 0.0
    # drain brackets restore the prior state; mid-drain faults land in
    # the resume state
    h.begin_drain()
    assert h.healthz()[0] == 503 and gauge.value == 2.0
    h.record_fault("fetch")
    assert h.state == "draining"
    h.end_drain()
    assert h.state == "degraded"
    h.fail("storm")
    assert h.state == "failed" and h.healthz()[0] == 503
    h.record_fault("x")
    h.record_progress()
    assert h.state == "failed"  # terminal
    trans = {dict(k)["to"]: v for k, v in parse_prometheus_text(
        reg.to_prometheus_text())
        ["serving_health_transitions_total"].items()}
    assert trans["failed"] == 1.0 and trans["degraded"] == 2.0


# --- the chaos oracle, seam by seam -----------------------------------------


def test_admit_error_recovers_with_parity(model):
    """A device error escaping the FIRST admission call: both requests
    in the batch are retried after backoff, the engine rebuilds without
    recompiling, and every completion is bit-identical to solo."""
    cfg, params, mesh = model
    plan = FaultPlan([FaultSpec("admit", 0, "error")])
    eng = _mk_engine(cfg, params, mesh, plan)
    eng.warmup()  # apex: noqa[TIER1-COST]: chaos recovery parity needs a warmed engine so the guard stays armed through rebuild
    sizes0 = eng.compiled_cache_sizes()
    rcfg = ResilienceConfig(backoff_base_s=0.005)
    sched = Scheduler(eng, resilience=rcfg)
    reqs = _reqs(2, seed0=7100)
    for r in reqs:
        sched.submit(r)
    sched.run_until_idle()
    assert len(plan.injected) == 1
    assert all(c.finish_reason == FINISH_LENGTH
               for c in sched.completions.values())
    _assert_parity(cfg, params, mesh, sched, reqs)
    s = sched.summary()
    assert s["rebuilds"] == 1.0 and s["retries"] == 2.0
    # recovery reuses the compiled init program — no recompilation
    assert eng.compiled_cache_sizes() == sizes0
    errs = [e for e in sched.pop_events() if e.error is not None]
    assert len(errs) == 2 and all(not e.finished for e in errs)


def test_dispatch_and_fetch_errors_recover(model):
    """Raised errors at the dispatch and fetch seams (separate runs):
    live requests are retried and finish with solo parity; the poisoned
    engine refuses device calls until the scheduler rebuilds it."""
    cfg, params, mesh = model
    for point in ("dispatch", "fetch"):
        plan = FaultPlan([FaultSpec(point, 1, "error")])
        eng = _mk_engine(cfg, params, mesh, plan)
        sched = Scheduler(eng, pipeline_depth=2,
                          resilience=ResilienceConfig(backoff_base_s=0.005))
        reqs = _reqs(3, seed0=7200, max_tokens=7)
        for r in reqs:
            sched.submit(r)
        sched.run_until_idle()
        assert len(plan.injected) == 1, point
        assert all(c.finish_reason == FINISH_LENGTH
                   for c in sched.completions.values()), point
        _assert_parity(cfg, params, mesh, sched, reqs)
        assert sched.summary()["rebuilds"] == 1.0, point
        assert sched.health.state in ("ok", "degraded")


def test_poisoned_engine_refuses_until_rebuild(model):
    """Failure isolation at the engine level: after a poisoning fault,
    every device call raises EngineFault until rebuild_slots()."""
    cfg, params, mesh = model
    plan = FaultPlan([FaultSpec("dispatch", 0, "error")])
    eng = _mk_engine(cfg, params, mesh, plan)
    eng.admit(0, [1, 2, 3], 5)
    with pytest.raises(EngineFault, match="injected"):
        eng.step_async()
    assert eng.poisoned
    for call in (eng.step_async, lambda: eng.admit(1, [4], 2),
                 lambda: eng.retire(0)):
        with pytest.raises(EngineFault, match="poisoned"):
            call()
    eng.rebuild_slots()
    assert not eng.poisoned
    eng.admit(0, [1, 2, 3], 5)
    eng.step()  # serves again


def test_retire_error_recovers(model):
    """A device error escaping the deadline-retire call: the expiring
    request still completes with timeout (its tokens are host-side),
    the batch-mate is replayed with full parity, and the engine
    rebuilds — retire was the one seam recovery used to not cover."""
    cfg, params, mesh = model
    plan = FaultPlan([FaultSpec("retire", 0, "error")])
    eng = _mk_engine(cfg, params, mesh, plan)
    now = [0.0]
    sched = Scheduler(eng, clock=lambda: now[0],
                      sleep=lambda s: now.__setitem__(0, now[0] + s),
                      resilience=ResilienceConfig(backoff_base_s=0.0))
    doomed = Request("doomed", [1, 2, 3], max_tokens=10, deadline=5.0)
    (mate,) = _reqs(1, seed0=7950, max_tokens=8)
    sched.submit(doomed)
    sched.submit(mate)
    sched.step()   # both admitted, first chunk decoded
    now[0] = 6.0   # the deadline lands; retire raises
    sched.run_until_idle()
    assert len(plan.injected) == 1
    dc = sched.completions["doomed"]
    assert dc.finish_reason == FINISH_TIMEOUT and len(dc.tokens) >= 1
    _assert_parity(cfg, params, mesh, sched, [mate])
    assert sched.summary()["rebuilds"] == 1.0
    assert not eng.poisoned


def test_nan_chunk_quarantines_only_affected_slot(model):
    """An invalid-token (NaN-poisoned) decode batch in slot 1's lane:
    the chunk is quarantined before any token leaks, slot 0's request
    replays for free (no error event, no retry charged), slot 1's is
    retried — and BOTH end bit-identical to solo."""
    cfg, params, mesh = model
    plan = FaultPlan([FaultSpec("fetch", 1, "nan", slots=(1,))])
    eng = _mk_engine(cfg, params, mesh, plan)
    sched = Scheduler(eng,
                      resilience=ResilienceConfig(backoff_base_s=0.005))
    reqs = _reqs(2, seed0=7300, max_tokens=8)
    for r in reqs:
        sched.submit(r)
    sched.run_until_idle()
    assert len(plan.injected) == 1
    _assert_parity(cfg, params, mesh, sched, reqs)
    s = sched.summary()
    assert s["rebuilds"] == 1.0
    assert s["retries"] == 1.0  # only the poisoned lane is charged
    errs = [e for e in sched.pop_events() if e.error is not None]
    assert [e.request_id for e in errs] == [reqs[1].request_id]


@pytest.mark.parametrize("specs", [
    [FaultSpec("fetch", 2, "nan", slots=(0,))],
    # a SECOND fault landing mid-replay: the snapshot must only grow
    # (a shrinking snapshot re-emitted the already-streamed tail as
    # duplicate events — the regression this pins)
    [FaultSpec("fetch", 2, "nan", slots=(0,)),
     FaultSpec("dispatch", 5, "error")],
], ids=["single", "fault_mid_replay"])
def test_stream_events_survive_replay_without_duplicates(model, specs):
    """The event stream under mid-decode faults carries each token
    exactly once per request, in order, despite the replay(s)."""
    cfg, params, mesh = model
    plan = FaultPlan(specs)
    eng = _mk_engine(cfg, params, mesh, plan)
    sched = Scheduler(eng,
                      resilience=ResilienceConfig(backoff_base_s=0.005))
    reqs = _reqs(2, seed0=7350, max_tokens=9)
    for r in reqs:
        sched.submit(r)
    streams = {r.request_id: [] for r in reqs}
    while sched.queue or sched.active or sched._inflight:
        sched.step()
        for e in sched.pop_events():
            if e.token is not None:
                streams[e.request_id].append(e.token)
        wait = sched._backoff_wait_s()
        if wait is not None:
            sched.sleep(wait)
    assert len(plan.injected) == len(specs)
    for r in reqs:
        assert streams[r.request_id] == sched.completions[
            r.request_id].tokens, r.request_id
    assert sched.summary()["tokens_emitted"] == sum(
        len(c.tokens) for c in sched.completions.values())
    _assert_parity(cfg, params, mesh, sched, reqs)


def test_nan_at_admission_quarantines(model):
    """A garbage first token out of the admission forward (NaN-poisoned
    prefill) is caught before any event leaks; the bad row is retried,
    its batch-mate replays free, parity holds for both."""
    cfg, params, mesh = model
    plan = FaultPlan([FaultSpec("admit", 0, "nan", slots=(0,))])
    eng = _mk_engine(cfg, params, mesh, plan)
    sched = Scheduler(eng,
                      resilience=ResilienceConfig(backoff_base_s=0.005))
    reqs = _reqs(2, seed0=7400)
    for r in reqs:
        sched.submit(r)
    sched.run_until_idle()
    assert len(plan.injected) == 1
    _assert_parity(cfg, params, mesh, sched, reqs)
    s = sched.summary()
    assert s["rebuilds"] == 1.0 and s["retries"] == 1.0


def test_retry_exhaustion_errors_out_cleanly(model):
    """A request whose admissions keep faulting exhausts its bounded
    retries and completes with the ``error`` finish reason (terminal
    error event, health degraded) — while an untouched request on the
    other slot still completes with full parity."""
    cfg, params, mesh = model
    # one slot + zero backoff: the victim heads the queue, so admit
    # calls 0/1/2 are all ITS (re)admissions — each NaN-poisoned at
    # row 0 — and it exhausts max_retries=2 on the third; the survivor
    # admits at call 3, which the plan leaves clean
    plan = FaultPlan([FaultSpec("admit", 0, "nan", slots=(0,)),
                      FaultSpec("admit", 1, "nan", slots=(0,)),
                      FaultSpec("admit", 2, "nan", slots=(0,))])
    eng = _mk_engine(cfg, params, mesh, plan, slots=1)
    rcfg = ResilienceConfig(max_retries=2, backoff_base_s=0.0)
    sched = Scheduler(eng, resilience=rcfg)
    victim, survivor = _reqs(2, seed0=7500)
    sched.submit(victim)
    sched.submit(survivor)
    sched.run_until_idle()
    assert len(plan.injected) == 3
    vc = sched.completions[victim.request_id]
    assert vc.finish_reason == FINISH_ERROR and vc.tokens == []
    _assert_parity(cfg, params, mesh, sched, [survivor])
    finals = [e for e in sched.pop_events()
              if e.error is not None and e.finished]
    assert [e.request_id for e in finals] == [victim.request_id]
    assert finals[0].finish_reason == FINISH_ERROR
    assert sched.summary()["retries"] == 2.0  # bounded, then done
    assert sched.health.state in ("ok", "degraded")  # recovered or not,
    # never dead — the survivor's healthy chunks may have restored ok


def test_rebuild_storm_fails_terminally_without_crashing(model):
    """Recovery that cannot make progress (every admission faults,
    back to back) trips max_consecutive_rebuilds: the health machine
    goes terminal, every request gets an ``error`` outcome, the
    process survives, and new submissions are refused with
    EngineFailed."""
    cfg, params, mesh = model
    plan = FaultPlan([FaultSpec("admit", i, "error") for i in range(6)])
    eng = _mk_engine(cfg, params, mesh, plan)
    rcfg = ResilienceConfig(max_retries=10, backoff_base_s=0.001,
                            max_consecutive_rebuilds=2)
    sched = Scheduler(eng, resilience=rcfg)
    reqs = _reqs(2, seed0=7600)
    for r in reqs:
        sched.submit(r)
    sched.run_until_idle()  # exits cleanly: everything aborted
    assert sched.health.state == "failed"
    assert all(c.finish_reason == FINISH_ERROR
               for c in sched.completions.values())
    assert set(sched.completions) == {r.request_id for r in reqs}
    with pytest.raises(EngineFailed):
        sched.submit(Request("late", [1, 2], max_tokens=2))
    sched.step()  # terminal tick is a no-op, not a crash


# --- overload protection ----------------------------------------------------


def test_queue_full_structured_hint_and_flood(model):
    cfg, params, mesh = model
    plan = FaultPlan([FaultSpec("submit", 2, "flood")])
    eng = _mk_engine(cfg, params, mesh, plan)
    reg = Registry()
    sched = Scheduler(eng, max_queue=1, registry=reg)
    # a measured chunk latency drives the retry-after estimate
    sched._chunk_ewma = 0.25
    sched.submit(Request("a", [1, 2], max_tokens=2))
    with pytest.raises(QueueFull) as ei:
        sched.submit(Request("b", [1, 2], max_tokens=2))
    assert ei.value.queue_depth == 1
    assert ei.value.retry_after_s == pytest.approx(0.25)
    # the injected flood rejects despite nominal room
    sched.queue.clear()
    with pytest.raises(QueueFull, match="flood") as ei:
        sched.submit(Request("c", [1, 2], max_tokens=2))
    assert ei.value.queue_depth == 1  # reported at capacity
    assert sched.health.state == "degraded"  # queue saturation degrades
    shed = {dict(k)["reason"]: v for k, v in parse_prometheus_text(
        reg.to_prometheus_text())["serving_requests_shed_total"].items()}
    assert shed["queue_full"] == 2.0 and shed["deadline"] == 0.0


def test_deadline_aware_shedding(model):
    """A queued request whose deadline is already unreachable (queue
    position × measured chunk latency) is shed IMMEDIATELY instead of
    rotting in the queue until expiry; a reachable deadline is not."""
    cfg, params, mesh = model
    eng = _mk_engine(cfg, params, mesh, slots=1)
    now = [100.0]
    reg = Registry()
    sched = Scheduler(eng, clock=lambda: now[0], registry=reg,
                      sleep=lambda s: now.__setitem__(0, now[0] + s))
    sched._chunk_ewma = 1.0  # the measured estimator, pinned
    sched.submit(Request("hog", [1, 2, 3], max_tokens=4))
    sched.submit(Request("doomed", [1, 2], max_tokens=2,
                         deadline=now[0] + 0.5))
    sched.submit(Request("fine", [1, 2], max_tokens=2,
                         deadline=now[0] + 300.0))
    sched.step()
    dc = sched.completions["doomed"]
    assert dc.finish_reason == FINISH_TIMEOUT and dc.tokens == []
    assert "fine" not in sched.completions  # reachable: kept
    shed = {dict(k)["reason"]: v for k, v in parse_prometheus_text(
        reg.to_prometheus_text())["serving_requests_shed_total"].items()}
    assert shed["deadline"] == 1.0
    # it was shed, not expired-in-place
    assert parse_prometheus_text(reg.to_prometheus_text())[
        "serving_queue_expired_total"][()] == 0.0
    sched.run_until_idle()
    assert sched.completions["fine"].finish_reason == FINISH_LENGTH
    # a request that fits the FREE slots admits this very tick and is
    # never shed, however tight its deadline looks against the EWMA
    sched.submit(Request("tight", [4, 5], max_tokens=2,
                         deadline=now[0] + 0.5))
    sched.run_until_idle()
    assert sched.completions["tight"].finish_reason == FINISH_LENGTH


def test_nan_in_released_lane_still_quarantines(model):
    """An out-of-vocab token in a lane with NO live request (slot
    released or never occupied) still quarantines the chunk: the
    poisoned step wrote the shared cache, so the buffers rebuild — but
    nobody is charged a retry, and the live request replays free with
    full parity."""
    cfg, params, mesh = model
    # slot 1 is never occupied; the fault corrupts its (dead) lane
    plan = FaultPlan([FaultSpec("fetch", 1, "nan", slots=(1,))])
    eng = _mk_engine(cfg, params, mesh, plan)
    sched = Scheduler(eng,
                      resilience=ResilienceConfig(backoff_base_s=0.005))
    (req,) = _reqs(1, seed0=7900, max_tokens=8)
    sched.submit(req)
    sched.run_until_idle()
    assert len(plan.injected) == 1
    s = sched.summary()
    assert s["rebuilds"] == 1.0 and s["retries"] == 0.0
    assert not [e for e in sched.pop_events() if e.error is not None]
    _assert_parity(cfg, params, mesh, sched, [req])


# --- watchdog + live /healthz e2e -------------------------------------------


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.read().decode("utf-8")
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode("utf-8")


def test_watchdog_and_live_healthz_ok_degraded_ok(model):
    """The e2e health acceptance: a hung dispatch (injected hang on a
    fake clock) trips the fetch watchdog; a LIVE /healthz scrape
    observes ok → degraded → ok as decode recovers, consistent with
    the serving_health_state gauge and the watchdog counter."""
    cfg, params, mesh = model
    now = [0.0]
    plan = FaultPlan(
        [FaultSpec("fetch", 1, "hang", hang_s=35.0)],
        hang_fn=lambda s: now.__setitem__(0, now[0] + s))
    eng = _mk_engine(cfg, params, mesh, plan)
    reg = Registry()
    sched = Scheduler(eng, registry=reg, clock=lambda: now[0],
                      sleep=lambda s: now.__setitem__(0, now[0] + s),
                      resilience=ResilienceConfig(watchdog_timeout_s=30.0,
                                                  recovery_chunks=2))
    server = MetricsServer(reg, health=sched.health.healthz).start()
    try:
        code, body = _get(server.url + "/healthz")
        assert (code, body) == (200, "ok\n")
        for r in _reqs(2, seed0=7700, max_tokens=12):
            sched.submit(r)
        sched.step()  # chunk 0: clean
        assert sched.health.state == "ok"
        sched.step()  # chunk 1: hangs 35s > 30s watchdog
        assert sched.health.state == "degraded"
        code, body = _get(server.url + "/healthz")
        assert code == 200 and body.startswith("degraded")
        assert "watchdog" in body
        gauge = parse_prometheus_text(reg.to_prometheus_text())
        assert gauge["serving_health_state"][()] == 1.0
        assert gauge["serving_watchdog_trips_total"][()] == 1.0
        # the hung chunk is excluded from the overload estimator — a
        # 35 s outlier folded into the EWMA would shed every deadlined
        # request against a latency the healthy engine does not have
        assert sched._chunk_ewma < 1.0
        sched.run_until_idle()  # healthy chunks recover the state
        code, body = _get(server.url + "/healthz")
        assert (code, body) == (200, "ok\n")
        assert parse_prometheus_text(reg.to_prometheus_text())[
            "serving_health_state"][()] == 0.0
        # no tokens were harmed: the hung chunk's values were valid
        assert sched.summary()["rebuilds"] == 0.0
    finally:
        server.stop()


def test_live_healthz_observes_draining(model):
    """Scheduler.drain() reads ``draining`` on a LIVE scrape taken
    mid-drain (a zero-second hang fault doubles as the observation
    hook), answers 503 to the balancer, and restores the prior state
    when the pipeline is empty."""
    cfg, params, mesh = model
    observed = []
    reg = Registry()
    server_box = []

    def hang_fn(_s):
        server = server_box[0]
        observed.append(_get(server.url + "/healthz"))

    plan = FaultPlan([FaultSpec("fetch", 1, "hang", hang_s=0.0)],
                     hang_fn=hang_fn)
    eng = _mk_engine(cfg, params, mesh, plan)
    sched = Scheduler(eng, registry=reg, pipeline_depth=2)
    server_box.append(MetricsServer(reg,
                                    health=sched.health.healthz).start())
    try:
        sched.submit(Request("d0", [3, 4, 5], max_tokens=10))
        sched.step()   # admit + dispatch chunk 0 (in flight at depth 2)
        sched.step()   # dispatch chunk 1, fetch chunk 0 (fetch idx 0)
        assert sched._inflight
        sched.drain()  # fetch idx 1 fires the scrape hook mid-drain
        assert observed == [(503, "draining\n")]
        assert not sched._inflight
        assert sched.health.state == "ok"  # restored after the drain
        code, body = _get(server_box[0].url + "/healthz")
        assert (code, body) == (200, "ok\n")
    finally:
        server_box[0].stop()


def test_registry_counters_reconcile_with_plan(model):
    """Counter consistency against a multi-fault plan: detected faults,
    rebuilds, retries, replays, and health transitions all reconcile
    with what the plan actually fired."""
    cfg, params, mesh = model
    plan = FaultPlan([FaultSpec("admit", 1, "error"),
                      FaultSpec("fetch", 3, "nan", slots=(0,))])
    eng = _mk_engine(cfg, params, mesh, plan)
    reg = Registry()
    sched = Scheduler(eng, registry=reg,
                      resilience=ResilienceConfig(backoff_base_s=0.005))
    reqs = _reqs(4, seed0=7800, max_tokens=7)
    for r in reqs:
        sched.submit(r)
    sched.run_until_idle()
    assert len(plan.injected) == 2
    _assert_parity(cfg, params, mesh, sched, reqs)
    p = parse_prometheus_text(reg.to_prometheus_text())
    faults = {dict(k)["cause"]: v
              for k, v in p["serving_faults_detected_total"].items()}
    assert faults["admit"] == 1.0
    assert faults["invalid_token"] == 1.0
    assert faults["dispatch"] == 0.0 and faults["fetch"] == 0.0
    s = sched.summary()
    assert p["serving_rebuilds_total"][()] == s["rebuilds"] == 2.0
    assert p["serving_retries_total"][()] == s["retries"]
    assert p["serving_replayed_tokens_total"][()] > 0.0
    # streamed tokens == sum over completions (replays suppressed)
    assert p["serving_tokens_emitted_total"][()] == sum(
        len(c.tokens) for c in sched.completions.values())
    # the engine stayed trace-stable through both recoveries
    sizes = eng.compiled_cache_sizes()
    for name in ("init", "step", "admit"):
        assert sizes[name] in (1, None), sizes


# --- randomized chaos soak (slow) + fast smoke ------------------------------


def _chaos_run(cfg, params, mesh, seed, n_reqs, n_faults):
    plan = FaultPlan.random(seed, n_faults, max_index=20,
                            slots=3, hang_s=0.0)
    eng = Engine(cfg, params, mesh,
                 EngineConfig(slots=3, max_prompt_len=8, max_seq_len=24,
                              decode_chunk=2), fault_plan=plan)
    sched = Scheduler(eng, pipeline_depth=2,
                      resilience=ResilienceConfig(backoff_base_s=0.002,
                                                  max_retries=4))
    reqs = _reqs(n_reqs, seed0=8000 + seed, max_tokens=6)
    pending = list(reqs)
    while pending or sched.queue or sched.active or sched._inflight:
        for r in pending[:2]:
            sched.submit(r)
        pending = pending[2:]
        sched.step()
        wait = sched._backoff_wait_s()
        if wait is not None:
            sched.sleep(wait)
    return plan, eng, sched, reqs


@pytest.mark.slow
def test_chaos_soak_randomized():
    """Randomized (seeded, exactly replayable) chaos soak: several
    seeds × many requests through a fault-riddled engine — every
    completion is either an explicit error outcome or bit-identical
    to solo generate, and recovery accounting stays consistent."""
    cfg = _cfg()
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=jax.devices()[:1])
    for seed in (1, 2, 3):
        plan, eng, sched, reqs = _chaos_run(cfg, params, mesh, seed,
                                            n_reqs=10, n_faults=4)
        assert len(sched.completions) == len(reqs)
        errored = {rid for rid, c in sched.completions.items()
                   if c.finish_reason == FINISH_ERROR}
        _assert_parity(cfg, params, mesh, sched, reqs, skip=errored)
        s = sched.summary()
        hard = [x for x in plan.injected if x.kind in ("error", "nan")]
        assert s["rebuilds"] <= len(hard)
        assert s["rebuilds"] >= len(
            [x for x in plan.injected if x.kind == "error"])


def test_chaos_smoke(devices8):
    """Tier-1 smoke slice of the randomized soak: one seed, small
    trace, same invariants."""
    cfg = _cfg()
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    plan, eng, sched, reqs = _chaos_run(cfg, params, mesh, seed=1,
                                        n_reqs=5, n_faults=3)
    assert len(sched.completions) == len(reqs)
    errored = {rid for rid, c in sched.completions.items()
               if c.finish_reason == FINISH_ERROR}
    _assert_parity(cfg, params, mesh, sched, reqs, skip=errored)


# --- atomic checkpoint writes (satellite) -----------------------------------


def _tiny_state():
    return {"w": np.arange(12, dtype=np.float32).reshape(3, 4),
            "step": np.int32(7)}


def test_checkpoint_atck_atomic_and_truncation_errors(tmp_path):
    """save_checkpoint_bin writes via same-dir temp + os.replace (no
    partial file can land at the destination), and any truncated
    ``.atck`` fails with the clear magic/truncation/CRC error — never
    struct/json garbage."""
    state = _tiny_state()
    path = str(tmp_path / "ck.atck")
    out = ckpt.save_checkpoint_bin(path, state)
    assert out == path
    # no temp droppings after a clean save
    assert sorted(p.name for p in tmp_path.iterdir()) == ["ck.atck"]
    back = ckpt.load_checkpoint_bin(path, state)
    np.testing.assert_array_equal(np.asarray(back["w"]), state["w"])
    raw = open(path, "rb").read()
    # cut points spanning every section: magic, header len, manifest,
    # blob, CRC trailer
    for cut in (0, 4, 10, 20, len(raw) - 30, len(raw) - 2):
        trunc = str(tmp_path / "trunc.atck")
        with open(trunc, "wb") as f:
            f.write(raw[:cut])
        with pytest.raises(ValueError,
                           match="atck|CRC|truncated") as ei:
            ckpt.load_checkpoint_bin(trunc, state)
        assert "ck.atck" not in str(ei.value)  # names the bad file
    # flipped blob byte: the CRC catches it
    bad = bytearray(raw)
    bad[len(raw) - 8] ^= 0xFF
    with open(str(tmp_path / "flip.atck"), "wb") as f:
        f.write(bytes(bad))
    with pytest.raises(ValueError, match="CRC"):
        ckpt.load_checkpoint_bin(str(tmp_path / "flip.atck"), state)


def test_checkpoint_npz_atomic(tmp_path):
    """The .npz fallback path is atomic too (temp + replace, no temp
    droppings), and still round-trips."""
    state = _tiny_state()
    path = str(tmp_path / "ck.npz")
    out = ckpt.save_checkpoint(path, state, force_npz=True)
    assert out == path
    assert sorted(p.name for p in tmp_path.iterdir()) == ["ck.npz"]
    back = ckpt.load_checkpoint(path, state, force_npz=True)
    np.testing.assert_array_equal(np.asarray(back["w"]), state["w"])
