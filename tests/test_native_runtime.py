"""Native host runtime: pack/unpack, crc32, record loader, .atck
checkpoints, TokenLoader.

Oracle pattern (SURVEY.md §4): native path vs pure-numpy reference must be
bit-identical; tests run with whichever backend built (the fallback covers
toolchain-less environments).
"""

import os
import zlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import _native as nat
from apex_tpu import checkpoint as ckpt
from apex_tpu import data as atdata


def test_pack_unpack_roundtrip():
    rng = np.random.default_rng(0)
    arrs = [
        rng.random(1000).astype(np.float32),
        np.arange(77, dtype=np.int32),
        rng.random((3, 5)),
        np.zeros((0,), np.float32),
        rng.random((64, 64)).astype(np.float16),
    ]
    buf = nat.pack_bytes(arrs)
    assert buf.nbytes == sum(a.nbytes for a in arrs)
    offs = np.cumsum([0] + [a.nbytes for a in arrs])[:-1].tolist()
    outs = nat.unpack_bytes(buf, [a.shape for a in arrs],
                            [a.dtype for a in arrs], offs)
    for a, b in zip(arrs, outs):
        assert a.dtype == b.dtype and np.array_equal(a, b)


def test_pack_with_explicit_offsets_and_padding():
    arrs = [np.full(4, 7, np.uint8), np.full(4, 9, np.uint8)]
    buf = nat.pack_bytes(arrs, offsets=[0, 8], total=16)
    assert list(buf[:4]) == [7] * 4
    assert list(buf[4:8]) == [0] * 4  # gap stays zeroed
    assert list(buf[8:12]) == [9] * 4


def test_crc32_matches_zlib():
    data = np.random.default_rng(1).integers(
        0, 255, 100_000, dtype=np.uint8)
    assert nat.crc32(data) == zlib.crc32(data.tobytes())
    assert nat.crc32(data, seed=123) == zlib.crc32(data.tobytes(), 123)


@pytest.fixture
def token_file(tmp_path):
    path = str(tmp_path / "toks.bin")
    np.arange(64 * 16, dtype=np.int32).reshape(64, 16).tofile(path)
    return path


def test_record_loader_epoch_coverage_and_sharding(token_file):
    ld = nat.RecordLoader(token_file, (16,), np.int32, batch=4,
                          rank=1, world=2, seed=0, shuffle=True)
    assert ld.num_records == 32
    seen = set()
    for _ in range(8):
        batch = ld.next()
        assert batch.shape == (4, 16)
        for row in batch:
            g = int(row[0]) // 16
            assert g % 2 == 1  # only rank-1 (odd) records
            seen.add(g)
    # one full epoch = every shard record exactly once
    assert len(seen) == 32
    ld.close()


def test_record_loader_deterministic(token_file):
    a = nat.RecordLoader(token_file, (16,), np.int32, batch=4, seed=7)
    b = nat.RecordLoader(token_file, (16,), np.int32, batch=4, seed=7)
    for _ in range(20):
        assert np.array_equal(a.next(), b.next())
    a.close()
    b.close()


def test_token_loader(tmp_path):
    path = str(tmp_path / "stream.bin")
    n = atdata.write_token_file(
        path, np.arange(10_000, dtype=np.int32), seq_len=32)
    assert n == 10_000 // 33
    ld = atdata.TokenLoader(path, seq_len=32, batch=4, shuffle=False)
    tok, tgt = ld.next()
    assert tok.shape == (4, 32) and tgt.shape == (4, 32)
    # targets are tokens shifted by one within the record
    assert jnp.array_equal(tok[:, 1:], tgt[:, :-1])
    ld.close()


def test_image_loader(tmp_path):
    path = str(tmp_path / "images.bin")
    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 256, (12, 8, 8, 3), dtype=np.uint8)
    lbls = rng.integers(0, 1000, 12).astype(np.int64)  # writer casts
    assert atdata.write_image_file(path, imgs, lbls) == 12
    ld = atdata.ImageLoader(path, (8, 8), batch=4, shuffle=False)
    assert ld.num_records == 12
    im, lb = ld.next()
    assert im.shape == (4, 8, 8, 3) and im.dtype == jnp.uint8
    assert lb.shape == (4,) and lb.dtype == jnp.int32
    assert jnp.array_equal(im, imgs[:4])
    assert jnp.array_equal(lb, lbls[:4].astype(np.int32))
    ld.close()

    norm = jax.jit(atdata.normalize_images)(im)
    ref = (np.asarray(im, np.float32) / 255.0
           - np.array(atdata.IMAGENET_MEAN, np.float32)) \
        / np.array(atdata.IMAGENET_STD, np.float32)
    assert np.allclose(np.asarray(norm), ref, atol=1e-6)


def test_image_loader_size_mismatch(tmp_path):
    """A wrong image_size must fail loudly, not reinterpret bytes."""
    path = str(tmp_path / "images.bin")
    atdata.write_image_file(
        path, np.zeros((3, 8, 8, 3), np.uint8), np.arange(3))
    with pytest.raises(ValueError, match="stores 8x8"):
        atdata.ImageLoader(path, (16, 16), batch=1)
    # 148 8x8 records (29008 payload bytes) coincidentally divide into
    # 49 592-byte 14x14 records — the geometry header must still reject
    atdata.write_image_file(
        path, np.zeros((148, 8, 8, 3), np.uint8), np.arange(148))
    with pytest.raises(ValueError, match="stores 8x8"):
        atdata.ImageLoader(path, (14, 14), batch=1)


def test_stale_abi_library_triggers_rebuild(monkeypatch, tmp_path):
    """A cached .so missing at_abi_version (pre-header ABI) must be
    rebuilt from source, not loaded."""
    if not nat.available():
        pytest.skip("no toolchain")
    import subprocess
    stale_src = tmp_path / "stale.cpp"
    stale_src.write_text('extern "C" { int not_the_abi() { return 0; } }')
    so = str(tmp_path / "libapex_tpu_host.so")
    subprocess.run(["g++", "-shared", "-fPIC", "-o", so, str(stale_src)],
                   check=True, capture_output=True)
    monkeypatch.setattr(nat, "_lib", None)
    monkeypatch.setattr(nat, "_SO", so)
    lib = nat._load()
    assert lib is not None  # rebuilt from _SRC and reloaded
    assert int(lib.at_abi_version()) == nat._ABI_VERSION


def test_record_loader_header_native_vs_fallback(token_file, monkeypatch,
                                                 tmp_path):
    """header_bytes skips the same prefix through both backends."""
    if not nat.available():
        pytest.skip("native runtime unavailable; fallback covered alone")
    path = str(tmp_path / "hdr.bin")
    with open(path, "wb") as f:
        f.write(b"\xff" * 8)                 # 8-byte junk header
        f.write(open(token_file, "rb").read())
    native = nat.RecordLoader(path, (16,), np.int32, batch=4,
                              shuffle=False, header_bytes=8)
    monkeypatch.setattr(nat, "_load", lambda: None)
    fallback = nat.RecordLoader(path, (16,), np.int32, batch=4,
                                shuffle=False, header_bytes=8)
    assert fallback._lib is None and native._lib is not None
    assert native.num_records == fallback.num_records == 64
    for _ in range(4):
        assert np.array_equal(native.next(), fallback.next())
    native.close()


def test_image_loader_rejects_headerless(tmp_path):
    """A raw byte blob (or a pre-header-format file) is not silently
    reinterpreted as images."""
    path = str(tmp_path / "raw.bin")
    np.zeros(16 + 196 * 4, np.uint8).tofile(path)
    with pytest.raises(ValueError, match="not an apex_tpu image file"):
        atdata.ImageLoader(path, (8, 8), batch=1)


def test_image_loader_rejects_future_version(tmp_path):
    path = str(tmp_path / "v9.bin")
    atdata.write_image_file(
        path, np.zeros((2, 8, 8, 3), np.uint8), np.arange(2))
    raw = bytearray(open(path, "rb").read())
    raw[4:8] = np.array([9], "<u4").tobytes()
    open(path, "wb").write(bytes(raw))
    with pytest.raises(ValueError, match="version 9"):
        atdata.ImageLoader(path, (8, 8), batch=1)


def test_image_loader_sharded(devices8, tmp_path):
    """dp-sharded placement: batch lands split over the mesh's dp axis."""
    from apex_tpu import mesh as mx

    path = str(tmp_path / "images.bin")
    rng = np.random.default_rng(1)
    imgs = rng.integers(0, 256, (16, 4, 4, 3), dtype=np.uint8)
    atdata.write_image_file(path, imgs, np.arange(16))
    mesh = mx.build_mesh(tp=1, devices=devices8)
    ld = atdata.ImageLoader(path, (4, 4), batch=8, mesh=mesh, shuffle=False)
    im, lb = ld.next()
    assert im.shape == (8, 4, 4, 3)
    assert len(im.sharding.device_set) == 8
    assert jnp.array_equal(lb, jnp.arange(8))
    ld.close()


def test_atck_checkpoint_roundtrip(tmp_path):
    state = {
        "w": jnp.arange(12, dtype=jnp.float32).reshape(3, 4),
        "b": jnp.ones((5,), jnp.bfloat16),
        "step": jnp.int32(7),
    }
    p = ckpt.save_checkpoint(str(tmp_path / "st.atck"), state)
    restored = ckpt.load_checkpoint(p, state)
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        assert a.dtype == b.dtype
        assert jnp.array_equal(a, b)


def test_atck_crc_detects_corruption(tmp_path):
    state = {"w": jnp.arange(1000, dtype=jnp.float32)}
    p = ckpt.save_checkpoint(str(tmp_path / "st.atck"), state)
    raw = bytearray(open(p, "rb").read())
    raw[200] ^= 0xFF  # flip a blob byte
    open(p, "wb").write(bytes(raw))
    with pytest.raises(ValueError, match="CRC"):
        ckpt.load_checkpoint(p, state)


def test_orbax_namedtuple_roundtrip(tmp_path):
    """The production (orbax) path must reassemble custom nodes."""
    from typing import NamedTuple

    class S(NamedTuple):
        a: jnp.ndarray
        b: jnp.ndarray

    state = S(a=jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
              b=jnp.int32(3))
    try:
        p = ckpt.save_checkpoint(str(tmp_path / "orb"), state)
    except Exception:
        pytest.skip("orbax unavailable")
    if not os.path.isdir(p):
        pytest.skip("orbax not installed; npz fallback covered elsewhere")
    restored = ckpt.load_checkpoint(p, state)
    assert isinstance(restored, S)
    assert jnp.array_equal(restored.a, state.a)
    assert int(restored.b) == 3


def test_abi_version_sources_agree():
    """ABI-drift guard: CLAUDE.md's convention says kAbiVersion
    (csrc/host_runtime.cpp) and _ABI_VERSION (_native/__init__.py) bump
    together on any C-ABI change — refuse the drift nothing else checks
    (a stale prebuilt .so is rejected at runtime, but a forgotten bump
    on one side would ship silently). The version parsing lives in ONE
    place — the ABI-LOCKSTEP lint rule — and this runtime test is a
    thin wrapper over it, plus the one thing lint cannot see: the
    LOADED module (whichever backend built) agrees with the sources."""
    from apex_tpu.analysis import parse_abi_versions

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cpp, py = parse_abi_versions(root)
    assert cpp is not None, \
        "kAbiVersion declaration not found in host_runtime.cpp"
    assert py is not None, \
        "_ABI_VERSION assignment not found in _native/__init__.py"
    assert cpp == py, (
        f"ABI drift: csrc kAbiVersion={cpp} != "
        f"_native _ABI_VERSION={py} — bump both together "
        f"(CLAUDE.md 'Native lib')")
    # and the loaded module (whichever backend built) agrees with them
    assert nat._ABI_VERSION == py
