"""Multi-tenant serving: batched per-slot LoRA adapters + tenant-aware
fair scheduling.

The contracts under test (``gpt`` multi-LoRA threading + the engine's
``adapter_slots`` pool + ``serving.tenancy``'s WFQ/rate-limit book):

- the PINNED zero adapter is numerically exact — base (adapter 0)
  traffic on an adapter-pool engine is token-identical to solo
  ``gpt.generate`` (which the pre-tenancy engine is itself pinned to);
- an adapter-carrying stream matches a solo merged-weight forward
  (``W + B A · alpha/r``) within per-dtype tolerance;
- a mixed-tenant batch equals per-tenant solo runs token-for-token —
  adapter ids are a per-row gather, rows never see batch-mates;
- parity composes: tp2-vs-tp1, paged + int8-KV + speculative decoding,
  and fault replay all hold with a heterogeneous adapter table, and
  the recompile guard stays flat across adapter registration and
  mixed-tenant admission churn (ids and pool content are DATA);
- weighted-fair queueing converges served-token shares to the weight
  ratio under a flood, priority aging rescues a starved tenant, and a
  rate-limited tenant 429s with Retry-After while other tenants'
  streams stay bit-identical to an uncontended run.
"""

import dataclasses
import http.client
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import mesh as mx
from apex_tpu.models import gpt
from apex_tpu.serving import Request, SamplingParams
from apex_tpu.serving.engine import Admission, Engine, EngineConfig
from apex_tpu.serving.resilience import FaultPlan, FaultSpec
from apex_tpu.serving.scheduler import Scheduler
from apex_tpu.serving.tenancy import (
    DEFAULT_TENANT,
    TenancyConfig,
    TenantBook,
    TenantThrottled,
)
from apex_tpu.transformer.testing import standalone_gpt_config

VOCAB = 96
RANK, ALPHA = 4, 8.0


def _cfg(**overrides):
    base = dict(vocab_size=VOCAB, seq_len=96)
    base.update(overrides)
    return standalone_gpt_config(**base)


def _mk_engine(cfg, params, mesh, *, fault_plan=None, **over):
    base = dict(slots=3, max_prompt_len=10, max_seq_len=24,
                decode_chunk=2, adapter_slots=4, adapter_rank=RANK,
                adapter_alpha=ALPHA)
    base.update(over)
    return Engine(cfg, params, mesh, EngineConfig(**base),
                  fault_plan=fault_plan).warmup()  # apex: noqa[TIER1-COST]: shared tiny adapter-engine builder — one def-line suppression covers the tenancy suite (the test_fleet _mk_sched shape)


def _solo_generate(cfg, params, mesh, prompt, n_new, sp: SamplingParams):
    pspecs = gpt.param_specs(cfg)
    key = (jax.random.PRNGKey(sp.seed)
           if sp.temperature > 0 and sp.seed is not None else None)
    out = jax.jit(jax.shard_map(
        lambda p, t: gpt.generate(
            cfg, p, t, n_new, temperature=sp.temperature,
            top_k=sp.top_k, top_p=sp.top_p, key=key, pad_token_id=0),
        mesh=mesh, in_specs=(pspecs, P(None, None)),
        out_specs=P(None, None), check_vma=False))(
            params, jnp.asarray([prompt], jnp.int32))
    return [int(t) for t in np.asarray(out)[0]]


def _requests(n, max_prompt_len, *, adapters=(0,), tenants=("default",),
              max_tokens=8, seed0=500):
    reqs = []
    for i in range(n):
        p_len = 1 + (7 * i + 3) % max_prompt_len
        prompt = [int(t) for t in jax.random.randint(
            jax.random.PRNGKey(seed0 + i), (p_len,), 0, VOCAB)]
        sp = (SamplingParams(temperature=0.9, top_k=7, seed=17 + i)
              if i % 3 == 1 else SamplingParams())
        reqs.append(Request(
            f"r{i}", prompt, max_tokens=max_tokens, sampling=sp,
            adapter=adapters[i % len(adapters)],
            tenant=tenants[i % len(tenants)]))
    return reqs


def _clone(reqs):
    return [dataclasses.replace(r, arrival_time=None) for r in reqs]


def _run(engine, reqs, **kw):
    sched = Scheduler(engine, **kw)
    for r in reqs:
        sched.submit(r)
    sched.run_until_idle()
    return sched


@pytest.fixture(scope="module")
def env(devices8):
    """One warmed adapter-pool engine + two seeded adapters, shared by
    the suite (each test rebuilds the slots it dirtied)."""
    cfg = _cfg()
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    eng = _mk_engine(cfg, params, mesh)
    a1 = eng.register_adapter(seed=7)
    a2 = eng.register_adapter(seed=9)
    ns = dataclasses.make_dataclass(
        "Env", ["cfg", "params", "mesh", "eng", "a1", "a2"])(
        cfg, params, mesh, eng, a1, a2)
    yield ns
    eng.close()


# --- adapter numerics oracles ------------------------------------------------


def test_zero_adapter_matches_solo_generate(env):
    """Base traffic on an adapter-pool engine is token-identical to
    solo ``gpt.generate`` — the pinned all-zero row 0 contributes an
    exact-zero delta, so the pool's presence costs nothing numerically
    (and the pre-tenancy engine is itself pinned to solo generate, so
    this is transitively the zero-adapter == pre-PR-base contract)."""
    env.eng.rebuild_slots()
    reqs = _requests(4, 10)
    sched = _run(env.eng, reqs)
    for r in reqs:
        solo = _solo_generate(env.cfg, env.params, env.mesh,
                              list(r.prompt), r.max_tokens, r.sampling)
        assert sched.completions[r.request_id].tokens == solo, \
            r.request_id


def test_adapter_stream_matches_merged_weights(env):
    """The merged-weight oracle: adapter-1 streams equal solo generate
    over ``merge_lora(params, W1, alpha)`` — token-for-token, with
    per-token logprobs inside the fp32 tolerance band (the adapter
    path computes the delta separately; the merge folds it into the
    kernels)."""
    env.eng.rebuild_slots()
    merged = gpt.merge_lora(env.cfg, env.params,
                            gpt.init_lora_weights(env.cfg, RANK, 7),
                            ALPHA)
    reqs = _requests(3, 10, adapters=(env.a1,))
    sched = _run(env.eng, reqs)
    for r in reqs:
        comp = sched.completions[r.request_id]
        solo = _solo_generate(env.cfg, merged, env.mesh,
                              list(r.prompt), r.max_tokens, r.sampling)
        assert comp.tokens == solo, (
            f"{r.request_id}: adapter {comp.tokens} != merged {solo}")
    # a registered adapter actually moves the stream (nonzero delta):
    # the same trace on the base adapter must diverge somewhere
    env.eng.rebuild_slots()
    base = _run(env.eng, _requests(3, 10))
    assert any(base.completions[r.request_id].tokens
               != sched.completions[r.request_id].tokens
               for r in reqs), "adapter delta never moved a token"


def test_mixed_adapter_batch_matches_solo_runs(env):
    """A heterogeneous adapter batch [base, a1, a2] emits exactly what
    each request emits riding the batch alone — the id table is a
    per-row gather; rows never see their batch-mates' weights."""
    reqs = _requests(3, 10, adapters=(0, env.a1, env.a2))
    env.eng.rebuild_slots()
    mixed = _run(env.eng, _clone(reqs))
    for i, r in enumerate(reqs):
        env.eng.rebuild_slots()
        solo = _run(env.eng, _clone([reqs[i]]))
        assert (mixed.completions[r.request_id].tokens
                == solo.completions[r.request_id].tokens), r.request_id


def test_tp2_matches_tp1_heterogeneous_adapters(devices8):
    """tp=2 sharding with a heterogeneous adapter table emits the tp=1
    streams: column-parallel sites shard B's output dim, row-parallel
    sites shard A's input dim with the rank-r intermediate psummed —
    the sharded delta is the unsharded delta."""
    cfg = _cfg()
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    reqs = _requests(3, 8, adapters=(0, 1, 2), max_tokens=6)

    def run_tp(tp):
        mesh = mx.build_mesh(tp=tp, devices=devices8[:tp])
        with _mk_engine(cfg, params, mesh, slots=2) as eng:
            eng.register_adapter(seed=7)
            eng.register_adapter(seed=9)
            sched = _run(eng, _clone(reqs))
            return {k: c.tokens for k, c in sched.completions.items()}

    assert run_tp(1) == run_tp(2)


def test_paged_int8_spec_adapter_parity(devices8):
    """The composition oracle: a paged + int8-KV + speculative engine
    with a heterogeneous adapter table emits the same streams as the
    contiguous plain-decode int8 engine — paged == contiguous and
    spec == plain both survive the adapter gather."""
    cfg = _cfg(kv_cache_dtype="int8")
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    reqs = _requests(3, 8, adapters=(0, 1, 2), max_tokens=6)

    def run(**over):
        with _mk_engine(cfg, params, mesh, slots=2, **over) as eng:
            eng.register_adapter(seed=7)
            eng.register_adapter(seed=9)
            sched = _run(eng, _clone(reqs))
            return {k: c.tokens for k, c in sched.completions.items()}

    assert run() == run(page_size=8, spec_k=2, spec_hist=12)


def test_adapter_fault_replay_exact(devices8):
    """A dispatch-seam fault mid-trace rebuilds the slots and replays
    interrupted adapter requests bit-identically — the adapter pool is
    never donated, so the replayed gather reads the same rows."""
    cfg = _cfg()
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    reqs = _requests(4, 10, adapters=(0, 1, 2))

    def run(plan):
        with _mk_engine(cfg, params, mesh, fault_plan=plan) as eng:
            eng.register_adapter(seed=7)
            eng.register_adapter(seed=9)
            sched = _run(eng, _clone(reqs))
            assert sched.health.state != "failed"
            return ({k: c.tokens for k, c in
                     sched.completions.items()}, sched.summary())

    clean, _ = run(None)
    faulted, s = run(FaultPlan([FaultSpec("dispatch", 2, "error")]))
    assert s["rebuilds"] >= 1.0, "the fault never fired"
    assert faulted == clean


def test_guard_flat_across_registration_and_churn(env):
    """The recompile guard stays flat across a THIRD adapter
    registration (the set program is warmed) and a mixed-tenant,
    mixed-adapter admission/decode churn — pool content and ids are
    data, never shapes."""
    env.eng.rebuild_slots()
    sizes0 = env.eng.compiled_cache_sizes()
    assert sizes0["adapter_init"] == 1 and sizes0["adapter_set"] == 1
    # trace built OUTSIDE the guard: jax.random prompt generation
    # compiles tiny host programs the guard would (rightly) flag
    reqs = _requests(5, 10, adapters=(0, env.a1, env.a2, 3),
                     tenants=("x", "y"), seed0=900)
    with env.eng.recompile_guard():
        a3 = env.eng.register_adapter(seed=11)
        assert a3 == 3
        sched = _run(env.eng, reqs,
                     tenancy=TenancyConfig(weights={"x": 2.0,
                                                    "y": 1.0}))
    assert len(sched.completions) == 5
    sizes = env.eng.compiled_cache_sizes()
    assert sizes == sizes0, (sizes0, sizes)


def test_engine_adapter_validation(env):
    """The loud edges: unregistered ids, adapter traffic on a
    pool-less engine, registration before warmup / past capacity /
    with bad shapes, and the prefix-pool × adapter exclusion."""
    eng = env.eng
    eng.rebuild_slots()
    with pytest.raises(ValueError, match="registered rows"):
        eng.admit_many([Admission(slot=0, prompt=[1, 2], max_tokens=2,
                                  adapter=3 + eng.adapters_registered)])
    with pytest.raises(ValueError, match="exactly one"):
        eng.register_adapter()
    bad = gpt.init_lora_weights(env.cfg, RANK + 1, 0)
    with pytest.raises(ValueError, match="ADAPTER-STATIC"):
        eng.register_adapter(bad, name="bad-rank")
    cfg2 = _cfg()
    eng2 = Engine(env.cfg, env.params, env.mesh, EngineConfig(
        slots=1, max_prompt_len=8, max_seq_len=16,
        adapter_slots=2, adapter_rank=RANK))
    with pytest.raises(ValueError, match="warmup"):
        eng2.register_adapter(seed=1)
    eng2.close()
    del cfg2
    # pool capacity: the shared engine has 4 rows (0 pinned + 3) —
    # fill up, then the next registration must overflow loudly
    while eng.adapters_registered < 3:
        eng.register_adapter(seed=100 + eng.adapters_registered)
    with pytest.raises(ValueError, match="full"):
        eng.register_adapter(seed=99)
    # idempotent by name: re-registering returns the existing id
    assert eng.register_adapter(seed=7) == env.a1


def test_scheduler_adapter_validation_and_prefix_exclusion(devices8):
    """submit() validates adapter ids up front (never a mid-serve
    fault), and adapter-carrying prompts skip the prefix pool — the
    pooled K/V is base-weight."""
    cfg = _cfg()
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    with _mk_engine(cfg, params, mesh, slots=2,
                    prefix_pool_slots=1) as eng:
        a1 = eng.register_adapter(seed=7)
        template = [int(t) for t in jax.random.randint(
            jax.random.PRNGKey(77), (9,), 0, VOCAB)]
        eng.register_prefix(template)
        sched = Scheduler(eng)
        with pytest.raises(ValueError, match="registered ids"):
            sched.submit(Request("bad", [1, 2], max_tokens=2,
                                 adapter=5))
        prompt = template[:8] + [3, 5]
        assert eng.match_prefix(prompt) is not None
        sched.submit(Request("hit", prompt, max_tokens=2))
        sched.submit(Request("skip", prompt, max_tokens=2,
                             adapter=a1))
        sched.run_until_idle()
        assert sched.summary()["prefix_hits"] == 1.0
        # engine-level belt-and-braces: the combination is rejected
        with pytest.raises(ValueError, match="base adapter"):
            eng.admit_many([Admission(
                slot=0, prompt=prompt, max_tokens=2, adapter=a1,
                prefix_page=0, prefix_len=8)])


# --- weighted-fair queueing + rate limits ------------------------------------


def test_tenant_book_wfq_and_aging_units():
    """The book in isolation: deficit counters converge picks to the
    weight ratio; a newcomer clamps to the live floor; aging drags a
    heavy-deficit tenant back after enough wait."""
    t = [0.0]
    book = TenantBook(TenancyConfig(weights={"a": 3.0, "b": 1.0},
                                    aging_per_s=1.0), lambda: t[0])
    picks = {"a": 0, "b": 0}
    for _ in range(400):
        who = book.pick({"a": 0.0, "b": 0.0})
        picks[who] += 1
        book.on_tokens(who, 10)
    ratio = picks["a"] / picks["b"]
    assert 2.5 <= ratio <= 3.5, picks
    # newcomer clamp: c starts at the floor, not at zero-forever credit
    book.note_backlogged("c")
    assert book.service_of("c") == min(book.service_of("a"),
                                       book.service_of("b"))
    # aging: b owes 50 normalized tokens more than a, but 60s of
    # head-of-line wait outweighs it
    book2 = TenantBook(TenancyConfig(aging_per_s=1.0), lambda: 0.0)
    book2.on_tokens("b", 50)
    assert book2.pick({"a": 0.0, "b": 0.0}) == "a"
    assert book2.pick({"a": 0.0, "b": 60.0}) == "b"
    # rejoin: an idle tenant returning does NOT bank its idle time —
    # its counter clamps UP to the backlogged floor, and never down
    bk = TenantBook(TenancyConfig(), lambda: 0.0)
    bk.on_tokens("a", 5)     # a served a little, then went idle
    bk.on_tokens("b", 100)   # b kept serving (enters at a's floor: 5)
    assert bk.service_of("b") == 105.0
    bk.rejoin("a", floor=bk.service_of("b"))
    assert bk.service_of("a") == bk.service_of("b")
    bk.rejoin("b", floor=0.0)
    assert bk.service_of("b") == 105.0  # rejoin never LOWERS a counter
    # overflow cap: past max_tenants, unseen ids fold into the shared
    # overflow identity (configured ids keep theirs)
    from apex_tpu.serving.tenancy import OVERFLOW_TENANT

    capped = TenantBook(TenancyConfig(weights={"vip": 2.0},
                                      max_tenants=2), lambda: 0.0)
    assert capped.admit_tenant("u1") == "u1"
    capped.stats("u1")
    assert capped.admit_tenant("u2") == "u2"
    capped.stats("u2")
    assert capped.admit_tenant("u3") == OVERFLOW_TENANT
    assert capped.admit_tenant("vip") == "vip"  # configured: exempt
    assert capped.admit_tenant("u1") == "u1"    # known: keeps identity


def test_tenant_bucket_units():
    """Token buckets: charges debit, refill is continuous, an
    over-budget charge reports the refill wait, and oversize requests
    clamp to the bucket capacity (gated, not unservable)."""
    t = [0.0]
    book = TenantBook(TenancyConfig(rates={"a": 10.0}, burst_s=2.0),
                      lambda: t[0])
    assert book.throttle("a", 20) is None          # full bucket
    wait = book.throttle("a", 10)
    assert wait == pytest.approx(1.0)              # needs 10 @ 10/s
    t[0] += 1.0
    assert book.throttle("a", 10) is None          # refilled
    assert book.throttle("unlimited", 10**6) is None
    # oversize: charge clamps to capacity (20), so it passes on a full
    # bucket instead of never
    t[0] += 10.0
    assert book.throttle("a", 10**6) is None


def test_tenancy_config_validation():
    for bad in (dict(weights={"a": 0.0}), dict(default_weight=0.0),
                dict(rates={"a": -1.0}), dict(burst_s=0.0),
                dict(aging_per_s=-1.0)):
        with pytest.raises(ValueError):
            TenancyConfig(**bad)


def test_wfq_fairness_and_aging_end_to_end(env):
    """Acceptance: under a 2-tenant flood with weights 3:1, mid-flood
    per-tenant served-token shares converge to 3:1 within ±15%, and a
    near-zero-weight third tenant still completes via priority aging
    (never starved)."""
    env.eng.rebuild_slots()
    tcfg = TenancyConfig(weights={"a": 3.0, "b": 1.0, "c": 0.001},
                         aging_per_s=50.0)
    sched = Scheduler(env.eng, tenancy=tcfg, max_queue=512)
    n = 24
    for i in range(n):
        for t in ("a", "b"):
            prompt = [int(x) for x in jax.random.randint(
                jax.random.PRNGKey(1000 + i), (3,), 0, VOCAB)]
            sched.submit(Request(f"{t}{i}", prompt, max_tokens=8,
                                 tenant=t))
    sched.submit(Request("c0", [5, 6, 7], max_tokens=4, tenant="c"))
    total = 2 * n + 1
    # steady-state shares: served-token DELTAS over the [1/4, 1/2]
    # completion window. The start cut drops the first admission wave
    # (deficits start equal, so it is round-robin by construction);
    # the end cut keeps BOTH tenants backlogged — the favoured tenant
    # drains its whole backlog ~3x sooner, and a window reaching into
    # the b-only tail would under-read the contended share
    marks = (total // 4, total // 2)
    snap = {}
    steps = 0
    while len(sched.completions) < total:
        sched.step()
        steps += 1
        assert steps < 50_000
        done = len(sched.completions)
        for mark in marks:
            if mark not in snap and done >= mark:
                ts = sched.tenant_summary()
                snap[mark] = (ts["a"]["tokens"], ts["b"]["tokens"])
    (a1, b1), (a2, b2) = (snap[m] for m in marks)
    ratio = (a2 - a1) / max(b2 - b1, 1.0)
    assert 3 * 0.85 <= ratio <= 3 * 1.15, (ratio, snap)
    assert sched.completions["c0"].tokens, "aged tenant starved"


def test_rate_limit_throttles_with_zero_drift(env):
    """Acceptance: a rate-limited tenant gets TenantThrottled (the
    API's 429) with a finite Retry-After while the other tenants'
    streams are bit-identical to an unthrottled run."""
    reqs = _requests(4, 10, tenants=("a", "b"), seed0=760)
    env.eng.rebuild_slots()
    clean = _run(env.eng, _clone(reqs))
    env.eng.rebuild_slots()
    sched = Scheduler(env.eng, tenancy=TenancyConfig(
        rates={"c": 1.0}, burst_s=8.0))
    throttled = []
    for r in _clone(reqs) + [
            Request("c0", [1, 2], max_tokens=8, tenant="c"),
            Request("c1", [1, 2], max_tokens=8, tenant="c")]:
        try:
            sched.submit(r)
        except TenantThrottled as e:
            assert e.tenant == "c" and e.retry_after_s > 0
            throttled.append(r.request_id)
    sched.run_until_idle()
    assert throttled == ["c1"]  # burst 8 covers c0's budget, not c1's
    for r in reqs:
        assert (sched.completions[r.request_id].tokens
                == clean.completions[r.request_id].tokens)
    ts = sched.tenant_summary()
    assert ts["c"]["throttled"] == 1.0
    assert sched.summary()["tenant_throttled"] == 1.0


def test_single_tenant_pops_strict_fifo(env):
    """A single-tenant queue is the historical FIFO scheduler —
    streams AND admission order are unchanged by the tenancy book."""
    reqs = _requests(5, 10, seed0=820)
    env.eng.rebuild_slots()
    plain = _run(env.eng, _clone(reqs))
    env.eng.rebuild_slots()
    fair = _run(env.eng, _clone(reqs), tenancy=TenancyConfig())
    assert ({k: c.tokens for k, c in plain.completions.items()}
            == {k: c.tokens for k, c in fair.completions.items()})


def test_fleet_rate_limit_is_one_bucket(env):
    """Fleet rate limits live at the ROUTER's ingress — one bucket per
    tenant fleet-wide (per-replica buckets would multiply the cap by
    the replica count)."""
    from apex_tpu.serving.fleet import Router

    env.eng.rebuild_slots()
    sched = Scheduler(env.eng)
    router = Router([sched], tenancy=TenancyConfig(
        rates={"c": 1.0}, burst_s=8.0))
    router.submit(Request("c0", [1, 2], max_tokens=8, tenant="c"))
    with pytest.raises(TenantThrottled) as e:
        router.submit(Request("c1", [1, 2], max_tokens=8, tenant="c"))
    assert e.value.retry_after_s > 0
    router.run_until_idle()
    assert router.completions["c0"].tokens
    sched.on_evict = None  # release the router's ownership hook


# --- API + analysis + replay -------------------------------------------------


def test_api_tenant_identity_models_and_429(env):
    """The wire surface: X-Tenant-Id beats the OpenAI `user` field,
    `/v1/models` lists registered adapters (routable via `model`), and
    a rate-limited tenant's request maps to 429 + Retry-After."""
    from apex_tpu.serving.api.server import ApiServer
    from apex_tpu.serving.api.tokenizer import ByteTokenizer

    env.eng.rebuild_slots()
    sched = Scheduler(env.eng, tenancy=TenancyConfig(
        rates={"capped": 4.0}, burst_s=1.0))
    # the byte codec needs one id per byte; the toy vocab is smaller,
    # so the tokenizer over-claims 256 and the test sticks to
    # token-id prompts within the engine's real vocab
    server = ApiServer(sched, ByteTokenizer(256), port=0).start()
    try:
        def post(body, headers=None):
            conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                              timeout=30)
            conn.request("POST", "/v1/completions", json.dumps(body),
                         {"Content-Type": "application/json",
                          **(headers or {})})
            resp = conn.getresponse()
            out = (resp.status, dict(resp.getheaders()),
                   json.loads(resp.read() or b"{}"))
            conn.close()
            return out

        # token-id prompts: the byte codec's printable range exceeds
        # this toy vocab, so the legacy list form keeps ids in range
        # header wins over user
        st, _, _ = post({"prompt": [1, 2, 3], "max_tokens": 2,
                         "user": "u-field"},
                        {"X-Tenant-Id": "u-header"})
        assert st == 200
        st, _, _ = post({"prompt": [1, 2, 3], "max_tokens": 2,
                         "user": "u-field2"})
        assert st == 200
        seen = sched.tenant_summary()
        assert "u-header" in seen and "u-field2" in seen
        assert "u-field" not in seen
        # /v1/models lists base + adapters with routable ids
        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=30)
        conn.request("GET", "/v1/models")
        models = json.loads(conn.getresponse().read())["data"]
        conn.close()
        ids = [m["id"] for m in models]
        assert ids[0] == server.model
        assert "adapter-seed-7" in ids and "adapter-seed-9" in ids
        assert server._resolve_adapter("adapter-seed-7") == env.a1
        assert server._resolve_adapter(server.model) == 0
        # adapter routing end-to-end: model= the adapter name
        st, _, _ = post({"prompt": [1, 2, 3], "max_tokens": 2,
                         "model": "adapter-seed-9"})
        assert st == 200
        # rate limit: burst 4 — the first request (2 tokens) passes,
        # the next (4) overdraws → 429 with Retry-After
        st, _, _ = post({"prompt": [1, 2, 3], "max_tokens": 2},
                        {"X-Tenant-Id": "capped"})
        assert st == 200
        st, hdrs, body = post({"prompt": [1, 2, 3], "max_tokens": 4},
                              {"X-Tenant-Id": "capped"})
        assert st == 429
        assert int(hdrs["Retry-After"]) >= 1
        assert body["error"]["code"] == "tenant_rate_limited"
    finally:
        server.stop()


def test_adapter_static_rule_synthetic(tmp_path):
    """ADAPTER-STATIC pos/neg: a len()-shaped adapter array fires, a
    config-derived one (and a non-adapter name) stays clean."""
    import textwrap

    from apex_tpu.analysis.core import run_analysis

    (tmp_path / "pyproject.toml").write_text("[project]\nname='s'\n")
    (tmp_path / "mod.py").write_text(textwrap.dedent("""
        import numpy as np

        def bad(live_requests, cfg):
            adapter_ids = np.zeros((len(live_requests),), np.int32)
            lora_pool = np.zeros((len(live_requests), 4), np.float32)
            return adapter_ids, lora_pool

        def good(cfg, arr):
            adapter_ids = np.zeros((cfg.slots,), np.int32)
            table = np.zeros((cfg.slots, cfg.max_pages), np.int32)
            scratch = np.zeros((len(arr),), np.float32)
            return adapter_ids, table, scratch
    """))
    res = run_analysis([str(tmp_path / "mod.py")], root=str(tmp_path),
                       rules=["ADAPTER-STATIC"])
    hits = [f for f in res.findings if f.rule == "ADAPTER-STATIC"]
    assert len(hits) == 2, [f.render() for f in hits]
    assert all(f.line in (5, 6) for f in hits), [f.render()
                                                for f in hits]


@pytest.mark.slow
def test_bundle_replay_with_adapters(devices8, tmp_path):
    """The black-box acceptance: a run with seeded adapters + tenants
    dumps a bundle whose replay re-registers the adapters from their
    recorded seeds and reproduces every stream bit-identically."""
    from apex_tpu.telemetry import FlightRecorder
    from apex_tpu.telemetry.replay import replay_bundle

    cfg = _cfg()
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    with _mk_engine(cfg, params, mesh) as eng:
        sched = Scheduler(
            eng, recorder=FlightRecorder(), bundle_dir=str(tmp_path),
            bundle_meta={"params": {"init_seed": 0}},
            tenancy=TenancyConfig(weights={"x": 2.0, "y": 1.0}))
        sched.register_adapter(seed=7)
        sched.register_adapter(seed=9)
        for r in _requests(4, 10, adapters=(0, 1, 2),
                           tenants=("x", "y")):
            sched.submit(r)
        sched.run_until_idle()
        path = sched.dump_bundle("tenancy-test")
        events = [e["event"] for e in
                  sched.recorder.to_dicts(sched.recorder.events())]
        assert events.count("adapter_register") == 2
    res = replay_bundle(path, verbose=False)
    assert not res["mismatches"], res["mismatches"]
    assert res["matched"] >= 4
