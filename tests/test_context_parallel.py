"""Ring / Ulysses context-parallel attention vs the full-sequence oracle.

Oracle: the single-chunk Pallas flash kernel (itself tested against the
jnp softmax reference) run on the unsharded sequence; both fwd outputs and
input grads must match across cp shardings, causal and not.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import mesh as mx
from apex_tpu.kernels import flash_attention
from apex_tpu.transformer.context_parallel import (
    ring_attention,
    ulysses_attention,
)

B, H, S, D = 2, 4, 64, 16


def _qkv(key):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, (B, H, S, D), jnp.float32) for k in ks)


def smap(f, mesh, in_specs, out_specs):
    return jax.jit(jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False))


def _ref(q, k, v, causal):
    def f(q, k, v):
        return flash_attention(q, k, v, causal=causal)
    out = f(q, k, v)
    # grads of a fixed scalar functional for comparison
    g = jax.grad(lambda q, k, v: jnp.sum(jnp.sin(f(q, k, v))), argnums=(0, 1, 2))(
        q, k, v)
    return out, g


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize(
    "impl",
    ["ring", "ring_flash", "ulysses", "ulysses_flash", "ulysses_bsh"])
def test_cp_attention_matches_full(devices8, causal, impl):
    mesh = mx.build_mesh(cp=4, devices=devices8[:4])
    q, k, v = _qkv(jax.random.PRNGKey(0))
    ref_out, ref_g = _ref(q, k, v, causal)

    if impl == "ring":
        def local(q, k, v):
            return ring_attention(q, k, v, causal=causal)
    elif impl == "ring_flash":
        # the TPU-default per-hop kernel path with (out, lse) hop merge —
        # including the lse cotangent through the merge weights
        def local(q, k, v):
            return ring_attention(q, k, v, causal=causal, impl="flash")
    elif impl == "ulysses":
        def local(q, k, v):
            return ulysses_attention(q, k, v, causal=causal)
    elif impl == "ulysses_bsh":
        # lane-packed layout: [b, h, s, d] shard ↔ [b, s, hidden]
        from apex_tpu.transformer.context_parallel import (
            ulysses_attention_bsh,
        )

        def local(q, k, v):
            to_bsh = lambda x: jnp.transpose(x, (0, 2, 1, 3)).reshape(
                x.shape[0], x.shape[2], -1)
            o = ulysses_attention_bsh(
                to_bsh(q), to_bsh(k), to_bsh(v), num_heads=H,
                causal=causal)
            return jnp.transpose(
                o.reshape(o.shape[0], o.shape[1], H, D), (0, 2, 1, 3))
    else:  # the Pallas-kernel branch must stay covered
        def local(q, k, v):
            return ulysses_attention(q, k, v, causal=causal, impl="flash")

    spec = P(None, None, "cp", None)  # shard seq dim
    out = smap(local, mesh, (spec,) * 3, spec)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               rtol=2e-5, atol=2e-5)

    def gfn(q, k, v):
        # differentiate the LOCAL loss: cross-rank grad contributions for
        # k/v arrive via the transposed ppermute/all_to_all, and the global
        # loss is the (implicit) sum of local losses
        return jax.grad(
            lambda q, k, v: jnp.sum(jnp.sin(local(q, k, v))),
            argnums=(0, 1, 2))(q, k, v)

    g = smap(gfn, mesh, (spec,) * 3, (spec,) * 3)(q, k, v)
    for a, b in zip(ref_g, g):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-4, atol=2e-5)


def test_ring_no_remat_matches(devices8):
    mesh = mx.build_mesh(cp=4, devices=devices8[:4])
    q, k, v = _qkv(jax.random.PRNGKey(1))
    spec = P(None, None, "cp", None)
    a = smap(lambda q, k, v: ring_attention(q, k, v, causal=True, remat=True),
             mesh, (spec,) * 3, spec)(q, k, v)
    b = smap(lambda q, k, v: ring_attention(q, k, v, causal=True, remat=False),
             mesh, (spec,) * 3, spec)(q, k, v)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_ulysses_head_divisibility(devices8):
    mesh = mx.build_mesh(cp=4, devices=devices8[:4])
    q = jnp.zeros((1, 3, 16, 8))  # 3 heads, cp=4 → error

    def f(q):
        return ulysses_attention(q, q, q)

    with pytest.raises(ValueError):
        smap(f, mesh, P(None, None, "cp", None), P(None, None, "cp", None))(q)


def test_zigzag_ring_matches_full(devices8):
    """zigzag layout + balanced schedule == full-sequence attention,
    forward and gradients (the permutation applied to the oracle)."""
    from apex_tpu.transformer.context_parallel import zigzag_slice

    mesh = mx.build_mesh(cp=4, devices=devices8[:4])
    q, k, v = _qkv(jax.random.PRNGKey(2))
    ref_out, ref_g = _ref(q, k, v, True)

    # rank r holds chunks (r, 2cp-1-r) of 8; out_specs concatenation
    # yields chunk order (0,7, 1,6, 2,5, 3,4)
    cp = 4
    c = S // (2 * cp)
    perm = np.concatenate(
        [np.arange(r * c, (r + 1) * c).tolist()
         + np.arange((2 * cp - 1 - r) * c, (2 * cp - r) * c).tolist()
         for r in range(cp)])

    def local(q, k, v):
        qz = zigzag_slice(q, 2)
        kz = zigzag_slice(k, 2)
        vz = zigzag_slice(v, 2)
        return ring_attention(qz, kz, vz, causal=True, zigzag=True)

    spec_full = P(None, None, None, None)
    spec_out = P(None, None, "cp", None)
    out = smap(local, mesh, (spec_full,) * 3, spec_out)(q, k, v)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(ref_out)[:, :, perm],
                               rtol=2e-5, atol=2e-5)

    # gradients: the local loss sums sin(out) over the zigzag shard; the
    # implicit global loss equals the full-sequence loss, so grads wrt
    # the (replicated) full q/k/v must match the oracle after psum
    def gfn(q, k, v):
        g = jax.grad(lambda a, b, c_: jnp.sum(jnp.sin(local(a, b, c_))),
                     argnums=(0, 1, 2))(q, k, v)
        return jax.tree.map(lambda x: lax.psum(x, "cp"), g)

    from jax import lax
    g = smap(gfn, mesh, (spec_full,) * 3, (spec_full,) * 3)(q, k, v)
    for a, b in zip(ref_g, g):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-4, atol=2e-5)


def test_zigzag_validation(devices8):
    mesh = mx.build_mesh(cp=4, devices=devices8[:4])
    q = jnp.zeros((1, 2, 8, 8))
    spec = P(None, None, "cp", None)
    with pytest.raises(ValueError, match="causal"):
        smap(lambda q: ring_attention(q, q, q, causal=False, zigzag=True),
             mesh, (spec,), spec)(q)
