"""Blockwise (chunked-XLA) attention + float16 kernel-boundary widening.

Mosaic has no f16 type, so every public Pallas wrapper widens float16
operands to f32 and narrows the result (kernels/_utils.widen_f16) —
these tests pin output dtypes and numerics for the fp16 (apex O2/O3
parity) path on every backend.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.kernels import flash_attention, layer_norm
from apex_tpu.kernels.blockwise_attention import blockwise_attention
from apex_tpu.kernels.flat_ops import adam_flat, l2norm_flat, scale_flat
from apex_tpu.kernels.softmax import scaled_upper_triang_masked_softmax
from apex_tpu.kernels.xentropy import softmax_cross_entropy


def _naive(q, k, v, causal):
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) / d ** 0.5
    if causal:
        sq = q.shape[2]
        s = jnp.where(jnp.tril(jnp.ones((sq, sq), bool)), s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v)


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_matches_naive(causal):
    q = jax.random.normal(jax.random.PRNGKey(0), (2, 2, 256, 32))
    k = jax.random.normal(jax.random.PRNGKey(1), (2, 2, 256, 32))
    v = jax.random.normal(jax.random.PRNGKey(2), (2, 2, 256, 32))
    got = blockwise_attention(q, k, v, causal=causal, q_chunk=64)
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(_naive(q, k, v, causal)),
                               rtol=1e-4, atol=1e-4)


def test_blockwise_grads_match_naive():
    q = jax.random.normal(jax.random.PRNGKey(0), (1, 2, 128, 16))
    k = jax.random.normal(jax.random.PRNGKey(1), (1, 2, 128, 16))
    v = jax.random.normal(jax.random.PRNGKey(2), (1, 2, 128, 16))

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    g1 = jax.grad(loss(lambda q, k, v: blockwise_attention(
        q, k, v, causal=True, q_chunk=32)), argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss(lambda q, k, v: _naive(q, k, v, True)),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_blockwise_nondivisible_shrinks_chunk():
    """A non-dividing q_chunk shrinks to a divisor (never a full-matrix
    fallback) and stays exact."""
    q = jax.random.normal(jax.random.PRNGKey(0), (1, 1, 100, 16))
    k, v = q + 1, q - 1
    got = blockwise_attention(q, k, v, causal=True, q_chunk=64)
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(_naive(q, k, v, True)),
                               rtol=1e-4, atol=1e-4)
    # prime length: degenerates to chunk 1 only for prime s <= q_chunk²
    got_p = blockwise_attention(q[:, :, :97], k[:, :, :97], v[:, :, :97],
                                causal=True, q_chunk=32)
    np.testing.assert_allclose(
        np.asarray(got_p),
        np.asarray(_naive(q[:, :, :97], k[:, :, :97], v[:, :, :97], True)),
        rtol=1e-4, atol=1e-4)


# -- f16 widening ----------------------------------------------------------

def test_layer_norm_f16_dtype_and_numerics():
    x = jax.random.normal(jax.random.PRNGKey(0), (64, 128), jnp.float16)
    w = jnp.ones((128,), jnp.float32)
    b = jnp.zeros((128,), jnp.float32)
    y = layer_norm(x, w, b)
    assert y.dtype == jnp.float16
    ref = layer_norm(x.astype(jnp.float32), w, b)
    np.testing.assert_allclose(np.asarray(y, np.float32), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)
    # grads flow and carry the input dtype
    g = jax.grad(lambda x: layer_norm(x, w, b).astype(jnp.float32).sum())(x)
    assert g.dtype == jnp.float16


def test_rms_norm_f16_dtype():
    from apex_tpu.kernels import rms_norm
    x = jax.random.normal(jax.random.PRNGKey(0), (32, 128), jnp.float16)
    w = jnp.ones((128,), jnp.float16)  # f16 weight must also widen
    y = rms_norm(x, w)
    assert y.dtype == jnp.float16
    assert bool(jnp.isfinite(y.astype(jnp.float32)).all())


def test_flash_attention_f16_dtype():
    q = jax.random.normal(jax.random.PRNGKey(0), (1, 2, 64, 32), jnp.float16)
    out = flash_attention(q, q, q, causal=True)
    assert out.dtype == jnp.float16
    assert bool(jnp.isfinite(out.astype(jnp.float32)).all())


def test_softmax_xentropy_f16():
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 2, 32, 32), jnp.float16)
    y = scaled_upper_triang_masked_softmax(x)
    assert y.dtype == jnp.float16
    rows = jnp.sum(y.astype(jnp.float32), -1)
    np.testing.assert_allclose(np.asarray(rows), 1.0, rtol=2e-3)

    logits = jax.random.normal(jax.random.PRNGKey(1), (8, 64), jnp.float16)
    tgt = jnp.arange(8) % 64
    loss = softmax_cross_entropy(logits, tgt)
    assert loss.dtype == jnp.float32
    g = jax.grad(lambda l: softmax_cross_entropy(l, tgt).sum())(logits)
    assert g.dtype == jnp.float16


def test_flat_ops_f16_buffers():
    n = 2048
    p16 = jnp.full((n,), 0.5, jnp.float16)
    g16 = jnp.full((n,), 2.0, jnp.float16)
    outs, found = scale_flat([g16], 0.5)
    assert outs[0].dtype == jnp.float16
    np.testing.assert_allclose(np.asarray(outs[0], np.float32), 1.0)
    assert not bool(found)
    assert float(l2norm_flat([g16])) == pytest.approx(
        np.sqrt(n * 4.0), rel=1e-3)
    m = jnp.zeros((n,), jnp.float32)
    v = jnp.zeros((n,), jnp.float32)
    new_p, new_m, new_v = adam_flat(
        [p16], [g16], [m], [v], lr=0.1, b1=0.9, b2=0.999, eps=1e-8,
        weight_decay=0.0, bias_correction1=0.1, bias_correction2=0.001)
    assert new_p[0].dtype == jnp.float16
    assert new_m[0].dtype == jnp.float32
    assert bool(jnp.isfinite(new_p[0].astype(jnp.float32)).all())
