"""ZeRO-3 / FSDP param sharding (beyond parity: the reference stops at
ZeRO-1/2 in distributed_fused_{adam,lamb} (U)).

Oracle: fsdp=True must train bit-for-tolerance identically to the
replicated model on the same mesh — the all-gather/psum_scatter pair is
exact up to fp reduction order."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import mesh as mx
from apex_tpu.amp import ScalerConfig
from apex_tpu.models import gpt, training
from apex_tpu.optimizers import fused_adam, fused_sgd

CFG = dict(vocab_size=96, hidden_size=64, num_layers=2, num_heads=4,
           seq_len=32, compute_dtype=jnp.float32)


def _data(key, batch=8, seq=32, vocab=96):
    tok = jax.random.randint(key, (batch, seq), 0, vocab)
    return tok, jnp.roll(tok, -1, axis=1)


def _run(devices, *, fsdp, tp=1, pp=1, n_micro=1, steps=3, **cfg_kw):
    cfg = gpt.GPTConfig(fsdp=fsdp, remat=True, **{**CFG, **cfg_kw})
    mesh = mx.build_mesh(tp=tp, pp=pp, devices=devices)
    init_fn, step_fn = training.make_train_step(
        cfg, mesh, fused_sgd(0.1, layout="tree"), ScalerConfig(enabled=False),
        n_micro=n_micro)
    state = init_fn(jax.random.PRNGKey(0))
    tok, tgt = _data(jax.random.PRNGKey(1))
    losses = []
    for _ in range(steps):
        state, m = step_fn(state, tok, tgt)
        losses.append(float(m["loss"]))
    return losses, jax.device_get(state.params)


def test_fsdp_matches_replicated_dp8(devices8):
    ref_losses, ref_p = _run(devices8, fsdp=False)
    f_losses, f_p = _run(devices8, fsdp=True)
    np.testing.assert_allclose(f_losses, ref_losses, rtol=2e-5)
    for a, b in zip(jax.tree.leaves(ref_p), jax.tree.leaves(f_p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-6)


def test_fsdp_param_and_state_shardings(devices8):
    """Between steps the kernels and their optimizer moments live
    dp-sharded; LN/bias/embedding stay replicated."""
    cfg = gpt.GPTConfig(fsdp=True, remat=True, **CFG)
    mesh = mx.build_mesh(tp=1, devices=devices8)
    init_fn, _ = training.make_train_step(
        cfg, mesh, fused_adam(1e-3, layout="tree"),
        ScalerConfig(enabled=False))
    state = init_fn(jax.random.PRNGKey(0))
    qkv_spec = state.params["layers"]["attn"]["qkv"]["kernel"].sharding.spec
    assert "dp" in jax.tree.leaves(tuple(qkv_spec))
    ln_spec = state.params["layers"]["ln1"]["scale"].sharding.spec
    assert "dp" not in jax.tree.leaves(tuple(ln_spec))
    # tree-layout moments mirror the params
    m_spec = jax.tree.leaves(
        state.opt_state, is_leaf=lambda x: hasattr(x, "sharding"))
    specs = [x.sharding.spec for x in m_spec
             if hasattr(x, "ndim") and x.ndim == 4]
    assert any("dp" in jax.tree.leaves(tuple(s)) for s in specs)


def test_fsdp_tp2_matches_flat(devices8):
    ref_losses, _ = _run(devices8, fsdp=False)
    f_losses, _ = _run(devices8, fsdp=True, tp=2)
    np.testing.assert_allclose(f_losses, ref_losses, rtol=2e-4)


def test_fsdp_pp2_matches_flat(devices8):
    ref_losses, _ = _run(devices8, fsdp=False)
    f_losses, _ = _run(devices8, fsdp=True, pp=2, n_micro=2)
    np.testing.assert_allclose(f_losses, ref_losses, rtol=2e-4)


def test_fsdp_sp_composes(devices8):
    ref_losses, _ = _run(devices8, fsdp=False, tp=2,
                         sequence_parallel=True)
    f_losses, _ = _run(devices8, fsdp=True, tp=2, sequence_parallel=True)
    np.testing.assert_allclose(f_losses, ref_losses, rtol=2e-4)


def test_fsdp_clip_grad_norm(devices8):
    """The clip norm psums fsdp shards over dp: fsdp == replicated."""
    def run(fsdp):
        cfg = gpt.GPTConfig(fsdp=fsdp, remat=True, **CFG)
        mesh = mx.build_mesh(tp=1, devices=devices8)
        init_fn, step_fn = training.make_train_step(
            cfg, mesh, fused_sgd(0.1, layout="tree"), ScalerConfig(enabled=False),
            clip_grad_norm=0.5)
        state = init_fn(jax.random.PRNGKey(0))
        tok, tgt = _data(jax.random.PRNGKey(1))
        state, m = step_fn(state, tok, tgt)
        return float(m["grad_norm"])

    np.testing.assert_allclose(run(True), run(False), rtol=2e-5)


def test_fsdp_validation(devices8):
    mesh = mx.build_mesh(tp=1, devices=devices8)
    cfg = gpt.GPTConfig(fsdp=True, remat=True, **CFG)
    with pytest.raises(ValueError, match="tree"):
        training.make_train_step(
            cfg, mesh, fused_adam(1e-3, layout="flat"),
            ScalerConfig(enabled=False))
    bad = gpt.GPTConfig(fsdp=True, remat=True,
                        **{**CFG, "hidden_size": 36, "num_heads": 4})
    with pytest.raises(ValueError, match="divide"):
        training.make_train_step(
            bad, mesh, fused_sgd(0.1, layout="tree"),
            ScalerConfig(enabled=False))
    moe = gpt.GPTConfig(fsdp=True, remat=True,
                        **{**CFG, "num_experts": 4})
    with pytest.raises(ValueError, match="num_experts"):
        training.make_train_step(
            moe, mesh, fused_sgd(0.1, layout="tree"),
            ScalerConfig(enabled=False))
    # LAMB trust ratios are whole-leaf norms — wrong on a dp shard
    from apex_tpu.optimizers import fused_lamb
    with pytest.raises(ValueError, match="norms"):
        training.make_train_step(
            cfg, mesh, fused_lamb(1e-3, layout="tree"),
            ScalerConfig(enabled=False))
    # without remat the gathered kernels become backward residuals
    norem = gpt.GPTConfig(fsdp=True, remat=False, **CFG)
    with pytest.raises(ValueError, match="remat"):
        training.make_train_step(
            norem, mesh, fused_sgd(0.1, layout="tree"),
            ScalerConfig(enabled=False))
