"""MLP / FusedDense / fp16_utils parity tests.

Oracle pattern: apex tests/L0/run_mlp + run_fused_dense (U) — fused block
vs an unfused reference — and fp16_utils master-weight round trips.
"""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.amp import ScalerConfig
from apex_tpu.fp16_utils import (
    FP16Optimizer,
    master_params_to_model_params,
    network_to_half,
    prep_param_lists,
)
from apex_tpu.fused_dense import FusedDense, FusedDenseGeluDense
from apex_tpu.mlp import MLP
from apex_tpu.optimizers import fused_sgd


def test_mlp_matches_reference():
    m = MLP([8, 16, 4], activation="relu")
    params = m.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (5, 8))
    y = m.apply(params, x)
    ref = jnp.maximum(x @ params[0]["kernel"] + params[0]["bias"], 0)
    ref = ref @ params[1]["kernel"] + params[1]["bias"]
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-6)


def test_fused_dense_gelu_dense():
    fd = FusedDenseGeluDense(8, 32, 4)
    p = fd.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (3, 8))
    y = fd.apply(p, x)
    ref = jax.nn.gelu(x @ p["fc1"]["kernel"] + p["fc1"]["bias"],
                      approximate=True)
    ref = ref @ p["fc2"]["kernel"] + p["fc2"]["bias"]
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-6)
    d = FusedDense(8, 4)
    pd = d.init(jax.random.PRNGKey(2))
    np.testing.assert_allclose(
        np.asarray(d.apply(pd, x)),
        np.asarray(x @ pd["kernel"] + pd["bias"]), rtol=1e-6)


def test_network_to_half_keeps_norms_fp32():
    params = {
        "dense": {"kernel": jnp.ones((4, 4))},
        "layernorm_1": {"scale": jnp.ones((4,)), "bias": jnp.zeros((4,))},
        "step": jnp.zeros((), jnp.int32),
    }
    half = network_to_half(params, jnp.float16)
    assert half["dense"]["kernel"].dtype == jnp.float16
    assert half["layernorm_1"]["scale"].dtype == jnp.float32
    assert half["step"].dtype == jnp.int32  # non-float untouched


def test_fp16_optimizer_round_trip():
    model_params = {"w": jnp.ones((4,), jnp.float16) * 2.0}
    grads = {"w": jnp.ones((4,), jnp.float16)}
    opt = FP16Optimizer(fused_sgd(0.5), ScalerConfig(init_scale=4.0))
    st = opt.init(model_params)
    assert st.master_params["w"].dtype == jnp.float32
    scaled_grads = jax.tree.map(
        lambda g: g * st.scaler.loss_scale, grads)  # simulate scaled bwd
    new_model, st = opt.step(st, model_params, scaled_grads)
    # unscale folds into sweep: effective grad = 1, w <- 2 - 0.5
    np.testing.assert_allclose(np.asarray(new_model["w"], np.float32), 1.5)
    assert new_model["w"].dtype == jnp.float16

    # overflow: inf grads -> params unchanged, scale halves
    bad = {"w": jnp.full((4,), jnp.inf, jnp.float16)}
    new_model2, st2 = opt.step(st, new_model, bad)
    np.testing.assert_allclose(np.asarray(new_model2["w"], np.float32), 1.5)
    assert float(st2.scaler.loss_scale) < float(st.scaler.loss_scale)


def test_master_model_round_trip():
    model = {"w": jnp.ones((3,), jnp.bfloat16)}
    _, masters = prep_param_lists(model)
    masters = jax.tree.map(lambda x: x + 0.123, masters)
    back = master_params_to_model_params(model, masters)
    assert back["w"].dtype == jnp.bfloat16
