"""MoE-GPT integration: expert parallelism inside the full training step.

The load-bearing oracle: one train step on a pure-dp mesh must equal the
same step on a dp×ep mesh — same global batch, same init — which checks
the ep all_to_all dispatch, the /ep grad scaling of expert leaves, and
the pmean of everything else, end to end through the optimizer."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import mesh as mx
from apex_tpu.amp import ScalerConfig
from apex_tpu.models import gpt, training
from apex_tpu.optimizers import fused_adam, fused_sgd
from apex_tpu.transformer.testing import standalone_gpt_config


def _cfg(**kw):
    base = dict(num_experts=4, moe_top_k=2, moe_capacity_factor=4.0)
    base.update(kw)
    return standalone_gpt_config(**base)


def _data(batch=16, seq=32):
    tok = jax.random.randint(jax.random.PRNGKey(7), (batch, seq), 0, 256)
    tgt = jax.random.randint(jax.random.PRNGKey(8), (batch, seq), 0, 256)
    return tok, tgt


def _run(mesh, cfg, steps=2, opt=None):
    init_fn, step_fn = training.make_train_step(
        cfg, mesh, opt or fused_adam(1e-3, layout="tree"),
        ScalerConfig(enabled=False))
    state = init_fn(jax.random.PRNGKey(0))
    tok, tgt = _data()
    losses = []
    for _ in range(steps):
        state, m = step_fn(state, tok, tgt)
        losses.append(float(m["loss"]))
    return jax.device_get(state.params), losses


def test_moe_gpt_ep_step_equals_pure_dp(devices8):
    cfg = _cfg()
    p_dp, l_dp = _run(mx.build_mesh(devices=devices8), cfg)        # dp=8
    p_ep, l_ep = _run(mx.build_mesh(ep=2, devices=devices8), cfg)  # dp=4,ep=2
    np.testing.assert_allclose(l_ep, l_dp, rtol=1e-5, atol=1e-6)
    for (path, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(p_dp),
            jax.tree_util.tree_leaves_with_path(p_ep)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5,
            err_msg=str(path))


def test_moe_gpt_trains_on_tp_ep_dp(devices8):
    cfg = _cfg(num_layers=2)
    mesh = mx.build_mesh(tp=2, ep=2, devices=devices8)  # dp=2
    _, losses = _run(mesh, cfg, steps=4)
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_moe_gpt_aux_loss_in_objective(devices8):
    """moe_aux_coef must move the objective: same data, two coefs."""
    mesh = mx.build_mesh(devices=devices8)
    _, l0 = _run(mesh, _cfg(moe_aux_coef=0.0), steps=1)
    _, l1 = _run(mesh, _cfg(moe_aux_coef=1.0), steps=1)
    assert l1[0] > l0[0]  # aux loss is positive (~1 when balanced)


def test_moe_gpt_rejections(devices8):
    with pytest.raises(ValueError, match="sequence_parallel"):
        init_fn, step_fn = training.make_train_step(
            _cfg(sequence_parallel=True),
            mx.build_mesh(tp=2, devices=devices8),
            fused_adam(1e-3, layout="tree"), ScalerConfig(enabled=False))
        tok, tgt = _data()
        step_fn(init_fn(jax.random.PRNGKey(0)), tok, tgt)
    with pytest.raises(ValueError, match="tree"):
        training.make_train_step(
            _cfg(), mx.build_mesh(ep=2, devices=devices8),
            fused_sgd(1e-3), ScalerConfig(enabled=False))


def test_moe_gpt_cp_step_equals_pure_dp(devices8):
    """MoE × context parallelism: ring attention over cp with MoE FFNs;
    one train step on dp=4 x cp=2 must equal pure dp=8 (generous capacity
    so per-source-rank drop patterns cannot diverge)."""
    cfg_dp = _cfg()
    cfg_cp = _cfg(context_parallel=True)
    # SGD: post-step param diffs stay proportional to grad diffs (Adam
    # would amplify ring attention's tiny reassociation noise on
    # near-zero grads into full lr-sized deviations)
    sgd = lambda: fused_sgd(1e-2, layout="tree")
    p_dp, l_dp = _run(mx.build_mesh(devices=devices8), cfg_dp, opt=sgd())
    p_cp, l_cp = _run(mx.build_mesh(cp=2, devices=devices8), cfg_cp,
                      opt=sgd())
    # ring attention reassociates the softmax reduction — same tolerance
    # family as tests/test_gpt_context_parallel.py
    np.testing.assert_allclose(l_cp, l_dp, rtol=2e-4)
    for (path, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(p_dp),
            jax.tree_util.tree_leaves_with_path(p_cp)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5,
            err_msg=str(path))


def test_moe_gpt_pp_step_equals_pure_dp(devices8):
    """MoE × pipeline parallelism: the aux term rides the tick scan.
    CE-only objective (aux_coef=0) so the comparison is exact — the
    Switch aux estimator is computed per microbatch under pp (a product
    of per-batch means, nonlinear in the batch split), so only the CE
    part is split-invariant."""
    sgd = lambda: fused_sgd(1e-2, layout="tree")
    cfg = _cfg(moe_aux_coef=0.0)
    p_dp, l_dp = _run(mx.build_mesh(devices=devices8), cfg, opt=sgd())
    init_fn, step_fn = training.make_train_step(
        cfg, mx.build_mesh(pp=2, devices=devices8), sgd(),
        ScalerConfig(enabled=False), n_micro=2)
    state = init_fn(jax.random.PRNGKey(0))
    tok, tgt = _data()
    l_pp = []
    for _ in range(2):
        state, m = step_fn(state, tok, tgt)
        l_pp.append(float(m["loss"]))
    np.testing.assert_allclose(l_pp, l_dp, rtol=2e-4)
    p_pp = jax.device_get(state.params)
    for (path, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(p_dp),
            jax.tree_util.tree_leaves_with_path(p_pp)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5,
            err_msg=str(path))


def test_moe_gpt_pp_aux_flows(devices8):
    """Under pp the load-balance loss must still reach the objective."""
    mesh = mx.build_mesh(pp=2, devices=devices8)

    def one(coef):
        init_fn, step_fn = training.make_train_step(
            _cfg(moe_aux_coef=coef), mesh,
            fused_adam(1e-3, layout="tree"), ScalerConfig(enabled=False),
            n_micro=2)
        tok, tgt = _data()
        _, m = step_fn(init_fn(jax.random.PRNGKey(0)), tok, tgt)
        return float(m["loss"])

    assert one(1.0) > one(0.0)


def test_moe_gpt_pp_ep_step_equals_pure_dp(devices8):
    """Full composition: pp=2 x ep=2 x dp=2 (stage ring outside, expert
    all_to_all inside each tick) equals pure dp=8."""
    sgd = lambda: fused_sgd(1e-2, layout="tree")
    cfg = _cfg(moe_aux_coef=0.0)
    p_dp, l_dp = _run(mx.build_mesh(devices=devices8), cfg, opt=sgd())
    init_fn, step_fn = training.make_train_step(
        cfg, mx.build_mesh(pp=2, ep=2, devices=devices8), sgd(),
        ScalerConfig(enabled=False), n_micro=2)
    state = init_fn(jax.random.PRNGKey(0))
    tok, tgt = _data()
    l_x = []
    for _ in range(2):
        state, m = step_fn(state, tok, tgt)
        l_x.append(float(m["loss"]))
    np.testing.assert_allclose(l_x, l_dp, rtol=2e-4)
    for (path, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(p_dp),
            jax.tree_util.tree_leaves_with_path(
                jax.device_get(state.params))):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5,
            err_msg=str(path))


def test_moe_gpt_trains_on_pp_tp_ep(devices8):
    """pp x tp x ep (dp=1): every parallel axis at once, loss falls."""
    init_fn, step_fn = training.make_train_step(
        _cfg(), mx.build_mesh(pp=2, tp=2, ep=2, devices=devices8),
        fused_adam(1e-3, layout="tree"), ScalerConfig(enabled=False),
        n_micro=2)
    state = init_fn(jax.random.PRNGKey(0))
    tok, tgt = _data(batch=4)
    losses = []
    for _ in range(4):
        state, m = step_fn(state, tok, tgt)
        losses.append(float(m["loss"]))
    assert all(np.isfinite(losses)) and losses[-1] < losses[0]


def test_dense_gpt_on_ep_mesh_is_extra_dp(devices8):
    """A dense model on an ep>1 mesh: ep behaves as additional data
    parallelism (batch sharded over ("dp", "ep"), grads pmean'd)."""
    dense = _cfg(num_experts=0)
    p_a, l_a = _run(mx.build_mesh(devices=devices8), dense)
    p_b, l_b = _run(mx.build_mesh(ep=2, devices=devices8), dense)
    np.testing.assert_allclose(l_b, l_a, rtol=1e-5, atol=1e-6)
    for (path, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(p_a),
            jax.tree_util.tree_leaves_with_path(p_b)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5,
            err_msg=str(path))


def test_moe_gpt_checkpoint_roundtrip(tmp_path, devices8):
    """MoE train state (router + expert-stacked leaves, ep-sharded) saves
    and resumes through the native checkpoint path bit-exactly."""
    from apex_tpu import checkpoint as ckpt

    cfg = _cfg()
    mesh = mx.build_mesh(ep=2, devices=devices8)
    init_fn, step_fn = training.make_train_step(
        cfg, mesh, fused_adam(1e-3, layout="tree"),
        ScalerConfig(enabled=False))
    state = init_fn(jax.random.PRNGKey(0))
    tok, tgt = _data()
    state, _ = step_fn(state, tok, tgt)

    path = str(tmp_path / "moe.atck")
    ckpt.save_checkpoint(path, state)
    like = init_fn(jax.random.PRNGKey(1))  # different values, same tree
    restored = ckpt.load_checkpoint(path, like)
    for (p, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(jax.device_get(state)),
            jax.tree_util.tree_leaves_with_path(jax.device_get(restored))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=str(p))
    # resumed state steps cleanly
    state2, m = step_fn(restored, tok, tgt)
    assert np.isfinite(float(m["loss"]))
