"""KV-cache decoding (beyond parity — apex ships no inference path).

Oracle: greedy generation through the incremental decode path must equal
teacher-forced argmax through the training forward, token for token."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu import mesh as mx
from apex_tpu.models import gpt
from apex_tpu.transformer.testing import standalone_gpt_config

N_NEW = 5


def _generate(cfg, params, prompt, mesh):
    pspecs = gpt.param_specs(cfg)
    return jax.jit(jax.shard_map(
        lambda p, t: gpt.generate(cfg, p, t, N_NEW), mesh=mesh,
        in_specs=(pspecs, P(None, None)), out_specs=P(None, None),
        check_vma=False))(params, prompt)


def _teacher_forced(cfg, params, prompt, mesh):
    """Grow the sequence one argmax at a time through the full forward."""
    pspecs = gpt.param_specs(cfg)
    logits_fn = jax.jit(jax.shard_map(
        lambda p, t: gpt.logits(cfg, p, t), mesh=mesh,
        in_specs=(pspecs, P(None, None)),
        out_specs=P(None, None, "tp"), check_vma=False))
    toks = prompt
    out = []
    for _ in range(N_NEW):
        lg = logits_fn(params, toks)  # [b, s, vocab]
        nxt = jnp.argmax(
            lg[:, -1].astype(jnp.float32), -1).astype(jnp.int32)
        out.append(nxt)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    return jnp.stack(out, axis=1)  # [b, n_new]


def test_generate_matches_teacher_forced(devices8):
    cfg = standalone_gpt_config(vocab_size=96, seq_len=24)
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(1), (3, 8), 0, 96)
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    got = _generate(cfg, params, prompt, mesh)
    want = _teacher_forced(cfg, params, prompt, mesh)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_generate_tp2_matches_tp1(devices8):
    cfg = standalone_gpt_config(vocab_size=96, seq_len=24)
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(1), (3, 8), 0, 96)
    g1 = _generate(cfg, params, prompt,
                   mx.build_mesh(tp=1, devices=devices8[:1]))
    g2 = _generate(cfg, params, prompt,
                   mx.build_mesh(tp=2, devices=devices8[:2]))
    np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))


def test_generate_moe_matches_teacher_forced(devices8):
    """MoE decode: per-step routing with generous capacity (drop-free on
    both paths) must agree with the full teacher-forced forward."""
    cfg = standalone_gpt_config(vocab_size=96, seq_len=24, num_experts=4,
                                moe_top_k=2, moe_capacity_factor=8.0)
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(1), (3, 8), 0, 96)
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    got = _generate(cfg, params, prompt, mesh)
    want = _teacher_forced(cfg, params, prompt, mesh)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_generate_rejects_overflow(devices8):
    import pytest

    cfg = standalone_gpt_config(seq_len=8)
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="seq_len"):
        gpt.generate(cfg, params, jnp.zeros((1, 6), jnp.int32), 5)


def test_generate_sampling(devices8):
    """temperature > 0 samples (reproducibly per key) and stays in-vocab;
    tiny temperature converges to greedy."""
    cfg = standalone_gpt_config(vocab_size=96, seq_len=24)
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(1), (3, 8), 0, 96)
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    pspecs = gpt.param_specs(cfg)

    def run(temp, seed):
        return jax.jit(jax.shard_map(
            lambda p, t: gpt.generate(cfg, p, t, N_NEW, temperature=temp,
                                      key=jax.random.PRNGKey(seed)),
            mesh=mesh, in_specs=(pspecs, P(None, None)),
            out_specs=P(None, None), check_vma=False))(params, prompt)

    a = np.asarray(run(1.0, 7))
    b = np.asarray(run(1.0, 7))
    np.testing.assert_array_equal(a, b)  # same key -> same draw
    assert a.shape == (3, N_NEW) and (a >= 0).all() and (a < 96).all()
    cold = np.asarray(run(1e-4, 7))
    greedy = np.asarray(_generate(cfg, params, prompt, mesh))
    np.testing.assert_array_equal(cold, greedy)
    import pytest

    with pytest.raises(ValueError, match="PRNG key"):
        gpt.generate(cfg, params, prompt, N_NEW, temperature=1.0)


def test_filter_logits_top_k():
    logits = jnp.asarray([[1.0, 4.0, 2.0, 3.0, 0.0]])
    out = np.asarray(gpt._filter_logits(logits, top_k=2, top_p=1.0))
    neg = np.finfo(np.float32).min
    np.testing.assert_array_equal(out[0], [neg, 4.0, neg, 3.0, neg])
    # top_k >= vocab is a no-op
    np.testing.assert_array_equal(
        np.asarray(gpt._filter_logits(logits, top_k=5, top_p=1.0)), logits)


def test_filter_logits_top_p():
    # softmax of [2, 1, 0, -9] ≈ [.665, .245, .090, ~0]: top_p=0.7 keeps
    # {2.0} plus the first token past the boundary rule's mass check
    logits = jnp.asarray([[2.0, 1.0, 0.0, -9.0]])
    out = np.asarray(gpt._filter_logits(logits, top_k=0, top_p=0.7))
    neg = np.finfo(np.float32).min
    np.testing.assert_array_equal(out[0], [2.0, 1.0, neg, neg])
    # p=0.99 admits the 0.090-mass token but still drops the ~1e-5 tail
    out = np.asarray(gpt._filter_logits(logits, top_k=0, top_p=0.99))
    np.testing.assert_array_equal(out[0], [2.0, 1.0, 0.0, neg])
    # top_p=1.0 disables the filter entirely
    out = np.asarray(gpt._filter_logits(logits, top_k=0, top_p=1.0))
    np.testing.assert_array_equal(out[0], logits[0])


def test_filter_logits_warper_order():
    """Combined k+p measures nucleus mass on the RENORMALIZED top-k
    distribution (HF warper order): over {2.0, 1.0} the leader holds
    0.731 > 0.7, so p=0.7 keeps it alone — measuring on the full
    distribution (leader mass 0.665 < 0.7) would keep both."""
    logits = jnp.asarray([[2.0, 1.0, 0.0, -9.0]])
    out = np.asarray(gpt._filter_logits(logits, top_k=2, top_p=0.7))
    neg = np.finfo(np.float32).min
    np.testing.assert_array_equal(out[0], [2.0, neg, neg, neg])


def test_generate_top_k1_equals_greedy(devices8):
    """top_k=1 sampling collapses to argmax regardless of temperature."""
    cfg = standalone_gpt_config(vocab_size=96, seq_len=24)
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(1), (3, 8), 0, 96)
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    pspecs = gpt.param_specs(cfg)
    sampled = jax.jit(jax.shard_map(
        lambda p, t: gpt.generate(cfg, p, t, N_NEW, temperature=1.3,
                                  top_k=1, key=jax.random.PRNGKey(5)),
        mesh=mesh, in_specs=(pspecs, P(None, None)),
        out_specs=P(None, None), check_vma=False))(params, prompt)
    greedy = _generate(cfg, params, prompt, mesh)
    np.testing.assert_array_equal(np.asarray(sampled), np.asarray(greedy))


def test_generate_top_filters_validated(devices8):
    import pytest
    cfg = standalone_gpt_config(vocab_size=96, seq_len=24)
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    prompt = jnp.zeros((1, 4), jnp.int32)
    with pytest.raises(ValueError, match="temperature"):
        gpt.generate(cfg, params, prompt, 2, top_k=4)
    with pytest.raises(ValueError, match="top_p"):
        gpt.generate(cfg, params, prompt, 2, temperature=1.0, top_p=0.0,
                     key=jax.random.PRNGKey(0))


def test_generate_rejects_bidirectional(devices8):
    import pytest

    cfg = standalone_gpt_config(causal=False)
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="autoregressive"):
        gpt.generate(cfg, params, jnp.zeros((1, 4), jnp.int32), 2)


def test_prefill_logits_match_full_forward(devices8):
    """Bulk prefill's last-position logits equal the training forward's —
    and its cache continues decoding identically to the from-scratch
    per-token path (covered transitively by the teacher-forced oracle)."""
    cfg = standalone_gpt_config()
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    pspecs = gpt.param_specs(cfg)
    params = jax.jit(jax.shard_map(
        lambda k: gpt.init(cfg, k), mesh=mesh, in_specs=P(),
        out_specs=pspecs, check_vma=False))(jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 7), 0,
                                cfg.vocab_size)

    _, pre_lg = jax.jit(jax.shard_map(
        lambda p, t: gpt.prefill(cfg, p, t, max_len=8), mesh=mesh,
        in_specs=(pspecs, P(None, None)),
        out_specs=(P(), P(None, None)), check_vma=False))(params, prompt)
    full_lg = jax.jit(jax.shard_map(
        lambda p, t: gpt.logits(cfg, p, t), mesh=mesh,
        in_specs=(pspecs, P(None, None)),
        out_specs=P(None, None, "tp"), check_vma=False))(params, prompt)
    np.testing.assert_allclose(
        np.asarray(pre_lg), np.asarray(full_lg[:, -1], np.float32),
        rtol=2e-5, atol=2e-5)


def test_generate_single_new_token(devices8):
    """n_new=1 is pure prefill (empty decode scan)."""
    cfg = standalone_gpt_config()
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    pspecs = gpt.param_specs(cfg)
    params = jax.jit(jax.shard_map(
        lambda k: gpt.init(cfg, k), mesh=mesh, in_specs=P(),
        out_specs=pspecs, check_vma=False))(jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 6), 0,
                                cfg.vocab_size)
    out = jax.jit(jax.shard_map(
        lambda p, t: gpt.generate(cfg, p, t, 1), mesh=mesh,
        in_specs=(pspecs, P(None, None)), out_specs=P(None, None),
        check_vma=False))(params, prompt)
    lg = jax.jit(jax.shard_map(
        lambda p, t: gpt.logits(cfg, p, t), mesh=mesh,
        in_specs=(pspecs, P(None, None)),
        out_specs=P(None, None, "tp"), check_vma=False))(params, prompt)
    exp = jnp.argmax(lg[:, -1].astype(jnp.float32), -1).astype(jnp.int32)
    np.testing.assert_array_equal(np.asarray(out[:, 0]), np.asarray(exp))


def test_generate_under_cp_config(devices8):
    """A cp training config reused for generation: prefill/decode strip
    the sequence shardings (params are cp-replicated, so the stripped
    forward is exact) — output must equal the cp-free reference."""
    import dataclasses

    cfg = standalone_gpt_config()
    cfg_cp = dataclasses.replace(cfg, context_parallel=True)
    pspecs = gpt.param_specs(cfg)
    mesh1 = mx.build_mesh(tp=1, devices=devices8[:1])
    params = jax.jit(jax.shard_map(
        lambda k: gpt.init(cfg, k), mesh=mesh1, in_specs=P(),
        out_specs=pspecs, check_vma=False))(jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 4), 0,
                                cfg.vocab_size)
    ref = _generate(cfg, params, prompt, mesh1)

    mesh_cp = mx.build_mesh(cp=2, devices=devices8[:2])
    params_cp = jax.jit(jax.shard_map(
        lambda k: gpt.init(cfg, k), mesh=mesh_cp, in_specs=P(),
        out_specs=pspecs, check_vma=False))(jax.random.PRNGKey(0))
    out = jax.jit(jax.shard_map(
        lambda p, t: gpt.generate(cfg_cp, p, t, N_NEW), mesh=mesh_cp,
        in_specs=(pspecs, P(None, None)), out_specs=P(None, None),
        check_vma=False))(params_cp, prompt)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def _beam(cfg, params, prompt, mesh, n_new, k):
    pspecs = gpt.param_specs(cfg)
    return jax.jit(jax.shard_map(
        lambda p, t: gpt.beam_search(cfg, p, t, n_new, num_beams=k),
        mesh=mesh, in_specs=(pspecs, P(None, None)),
        out_specs=(P(None, None, None), P(None, None)),
        check_vma=False))(params, prompt)


def test_beam_search_k1_equals_greedy(devices8):
    cfg = standalone_gpt_config(vocab_size=96, seq_len=24)
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(1), (3, 8), 0, 96)
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    seqs, scores = _beam(cfg, params, prompt, mesh, N_NEW, 1)
    greedy = _generate(cfg, params, prompt, mesh)
    np.testing.assert_array_equal(np.asarray(seqs[:, 0]),
                                  np.asarray(greedy))
    assert np.all(np.isfinite(np.asarray(scores)))


def test_beam_search_exhaustive_oracle(devices8):
    """With num_beams == vocab and a 2-token horizon the frontier covers
    every reachable prefix, so the top beam must be the global argmax
    sequence — checked against brute-force teacher-forced scoring of
    all vocab^2 continuations."""
    V, n_new = 8, 2
    cfg = standalone_gpt_config(vocab_size=V, seq_len=12)
    params = gpt.init(cfg, jax.random.PRNGKey(3))
    prompt = jax.random.randint(jax.random.PRNGKey(4), (2, 4), 0, V)
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    seqs, scores = _beam(cfg, params, prompt, mesh, n_new, V)

    pspecs = gpt.param_specs(cfg)
    logits_fn = jax.jit(jax.shard_map(
        lambda p, t: gpt.logits(cfg, p, t), mesh=mesh,
        in_specs=(pspecs, P(None, None)),
        out_specs=P(None, None, "tp"), check_vma=False))
    b, p_len = prompt.shape
    best_score = np.full((b,), -np.inf)
    best_seq = np.zeros((b, n_new), np.int64)
    for t0 in range(V):
        for t1 in range(V):
            cont = jnp.tile(jnp.asarray([[t0, t1]], jnp.int32), (b, 1))
            toks = jnp.concatenate([prompt, cont], axis=1)
            lg = np.asarray(logits_fn(params, toks), np.float32)
            lp = jax.nn.log_softmax(jnp.asarray(lg), axis=-1)
            s = (np.asarray(lp[:, p_len - 1, t0])
                 + np.asarray(lp[:, p_len, t1]))
            for i in range(b):
                if s[i] > best_score[i]:
                    best_score[i] = s[i]
                    best_seq[i] = (t0, t1)
    np.testing.assert_array_equal(np.asarray(seqs[:, 0]), best_seq)
    np.testing.assert_allclose(np.asarray(scores[:, 0]), best_score,
                               rtol=1e-4, atol=1e-5)
    # beams come back sorted
    assert np.all(np.diff(np.asarray(scores), axis=1) <= 1e-6)


def test_beam_search_tp2_matches_tp1(devices8):
    cfg = standalone_gpt_config(vocab_size=96, seq_len=24)
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0, 96)
    s1, sc1 = _beam(cfg, params, prompt,
                    mx.build_mesh(tp=1, devices=devices8[:1]), 4, 3)
    s2, sc2 = _beam(cfg, params, prompt,
                    mx.build_mesh(tp=2, devices=devices8[:2]), 4, 3)
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))
    np.testing.assert_allclose(np.asarray(sc1), np.asarray(sc2),
                               rtol=2e-5)


def test_beam_search_validation():
    import pytest
    cfg = standalone_gpt_config(vocab_size=16, seq_len=8)
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    prompt = jnp.zeros((1, 4), jnp.int32)
    with pytest.raises(ValueError, match="num_beams"):
        gpt.beam_search(cfg, params, prompt, 2, num_beams=17)
    with pytest.raises(ValueError, match="seq_len"):
        gpt.beam_search(cfg, params, prompt, 6, num_beams=2)


def test_generate_eos_early_stop(devices8):
    """Once a row emits eos, every later position is pad; positions up
    to and including the eos match the unconstrained greedy run."""
    cfg = standalone_gpt_config(vocab_size=96, seq_len=24)
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(1), (3, 8), 0, 96)
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    base = np.asarray(_generate(cfg, params, prompt, mesh))
    eos = int(base[0, 1])  # row 0's second token becomes the stop token
    pspecs = gpt.param_specs(cfg)
    out = np.asarray(jax.jit(jax.shard_map(
        lambda p, t: gpt.generate(cfg, p, t, N_NEW, eos_token_id=eos,
                                  pad_token_id=0),
        mesh=mesh, in_specs=(pspecs, P(None, None)),
        out_specs=P(None, None), check_vma=False))(params, prompt))
    for i in range(base.shape[0]):
        hits = np.where(base[i] == eos)[0]
        stop = hits[0] if hits.size else N_NEW - 1
        np.testing.assert_array_equal(out[i, :stop + 1],
                                      base[i, :stop + 1])
        assert np.all(out[i, stop + 1:] == 0)
    assert np.any(base[0] == eos)  # the forcing actually triggered


def test_beam_search_eos_freezes_beam(devices8):
    """k=1 beam search with eos equals greedy generate with eos, and a
    frozen beam's score stops changing at the eos position."""
    cfg = standalone_gpt_config(vocab_size=96, seq_len=24)
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(1), (3, 8), 0, 96)
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    base = np.asarray(_generate(cfg, params, prompt, mesh))
    eos = int(base[0, 1])
    pspecs = gpt.param_specs(cfg)

    def run(n_new):
        return jax.jit(jax.shard_map(
            lambda p, t: gpt.beam_search(cfg, p, t, n_new, num_beams=1,
                                         eos_token_id=eos,
                                         pad_token_id=0),
            mesh=mesh, in_specs=(pspecs, P(None, None)),
            out_specs=(P(None, None, None), P(None, None)),
            check_vma=False))(params, prompt)

    seqs, scores = run(N_NEW)
    greedy_eos = np.asarray(jax.jit(jax.shard_map(
        lambda p, t: gpt.generate(cfg, p, t, N_NEW, eos_token_id=eos,
                                  pad_token_id=0),
        mesh=mesh, in_specs=(pspecs, P(None, None)),
        out_specs=P(None, None), check_vma=False))(params, prompt))
    np.testing.assert_array_equal(np.asarray(seqs[:, 0]), greedy_eos)
    # row 0 finished at position 1: the score with a longer horizon is
    # identical (pad extensions are free)
    _, scores_short = run(2)
    np.testing.assert_allclose(float(scores[0, 0]),
                               float(scores_short[0, 0]), rtol=1e-6)
