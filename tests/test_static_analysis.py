"""apex_tpu.analysis — the static linter's own battery.

Three layers:

1. the merge gates: the full-tree run (``apex_tpu bench.py examples``,
   every rule) and the tests-tree TIER1-COST run are clean, fast
   (<15 s — pure-Python AST, no compile), and the active-suppression
   count is pinned so it can only go down;
2. per-rule positive/negative pairs over synthetic trees — every rule
   must FIRE on its synthetic violation and stay SILENT on the clean
   twin (a linter that cannot fire is indistinguishable from one that
   works);
3. the suppression mechanism itself: justified noqa silences and is
   counted, bare noqa is a finding, unused noqa is a finding, and a
   disabled rule's suppressions are out of scope for the run.

No jax/numpy anywhere in the analyzer (pinned by the purged-import
subprocess test at the bottom, same pattern as serving.api's).
"""

import os
import subprocess
import sys
import textwrap
import time

import pytest

from apex_tpu.analysis import parse_abi_versions
from apex_tpu.analysis.core import run_analysis, summary_dict

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: the allowlist pin (satellite contract: this number may only go
#: DOWN; new suppressions need to displace an old one or justify a
#: bump here with the review that approved it)
#: 25 -> 24 (fleet-router PR): test_fleet.py's shared tiny-replica
#: builder `_mk_sched` added one def-line suppression (same shape as
#: test_paged_cache's `_mk_engine`), displaced by slow-marking the
#: prefix-registration contract test (its two suppressions removed);
#: tier-1 runtime offset by slow-marking variant-redundant serving
#: oracles (see the `fleet-router tier-1 offset` markers)
#: 24 -> 22 (multi-tenant PR): test_tenancy.py's shared adapter-engine
#: builder `_mk_engine` added one def-line suppression, displaced by
#: slow-marking the two-engine scheduler prefix-detection composition
#: (its two suppressions removed) and the spec×constrained composition
#: (one removed) — see the `multi-tenant tier-1 offset` markers
#: 22 -> 21 (slo-observatory PR): test_slo.py is host-only (no warmup,
#: no new suppressions); the quantized+prefix+guard composition in
#: test_kv_cache was slow-marked as the tier-1 runtime offset and its
#: one suppression removed — see the `slo-observatory tier-1 offset`
#: marker
#: 21 -> 21 (durable-journal PR): test_journal.py's crash-recovery
#: oracle added one warmed-engine suppression, displaced by
#: slow-marking test_kv_cache's pool-reset-on-failed-insert corner
#: (register/match/admission stay tier-1 via the hit-parity oracle) —
#: see the `durable-journal tier-1 offset` marker
MAX_ACTIVE_SUPPRESSIONS = 21


def _rules_of(result):
    return sorted({f.rule for f in result.findings})


def _synth(tmp_path, files, targets=None, rules=None):
    (tmp_path / "pyproject.toml").write_text("[project]\nname='synth'\n")
    for rel, body in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(body))
    targets = targets or sorted({r.split("/")[0] for r in files})
    targets = [str(tmp_path / t) for t in targets]
    return run_analysis(targets, root=str(tmp_path), rules=rules)


# --------------------------------------------------------------------------
# merge gates
# --------------------------------------------------------------------------


def test_full_tree_clean_and_fast():
    t0 = time.monotonic()
    res = run_analysis(
        [os.path.join(REPO, "apex_tpu"), os.path.join(REPO, "bench.py"),
         os.path.join(REPO, "examples")], root=REPO)
    elapsed = time.monotonic() - t0
    assert not res.findings, "\n".join(f.render() for f in res.findings)
    assert res.exit_code == 0
    # pure-Python AST over ~16k lines; a budget blowout means someone
    # added quadratic work, not that the tree got bigger
    assert elapsed < 15.0, f"analysis took {elapsed:.1f}s (budget 15s)"
    s = summary_dict(res)
    assert s["exit_code"] == 0 and s["counts"] == {}


def test_tests_tree_tier1_battery_clean_and_pinned():
    res = run_analysis([os.path.join(REPO, "tests")], root=REPO,
                       rules=["TIER1-COST"])
    assert not res.findings, "\n".join(f.render() for f in res.findings)
    active = len(res.suppressions_used)
    # upper bound only: reaching zero (every warmup test slow-marked or
    # restructured) is the contract's ideal end state, not a failure
    assert active <= MAX_ACTIVE_SUPPRESSIONS, (
        f"{active} active TIER1-COST suppressions vs pin "
        f"{MAX_ACTIVE_SUPPRESSIONS} — the allowlist only shrinks; "
        f"mark new warmup tests slow or displace an old suppression")


def test_changed_mode_git_failure_is_a_usage_error(tmp_path):
    # a failed git query must not read as "nothing changed" — that
    # would let the pre-commit gate pass without linting anything
    (tmp_path / "pyproject.toml").write_text("[project]\nname='x'\n")
    (tmp_path / "mod.py").write_text("X = 1\n")
    with pytest.raises(ValueError, match="--changed"):
        run_analysis([str(tmp_path / "mod.py")], root=str(tmp_path),
                     changed_only=True)


def test_suppression_in_bench_visible_to_partial_runs(tmp_path):
    # METRIC-DRIFT anchors doc-side findings in bench.py; a justified
    # suppression there must silence them even when bench.py is not a
    # target of the (--changed-style) partial run
    files = {
        "apex_tpu/__init__.py": "",
        "apex_tpu/serving/__init__.py": "",
        "apex_tpu/serving/sched.py": '''
            def wire(registry):
                registry.counter("serving_ok_total", "")
        ''',
        "bench.py":
            'K = "serving_ghost_total"  # apex: noqa[METRIC-DRIFT]: trajectory key, deliberately unregistered\n',
        "docs/API.md": "`serving_ok_total`\n",
    }
    res = _synth(tmp_path, files, targets=["apex_tpu"])
    assert not res.findings, "\n".join(f.render() for f in res.findings)


def test_overlapping_targets_analyze_each_file_once(tmp_path):
    # `analysis pkg pkg/mod.py` must not load mod.py twice — that would
    # double every per-target finding and the pinned suppressions.active
    # count (the shrink-only contract number)
    (tmp_path / "pyproject.toml").write_text("[project]\nname='x'\n")
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "mod.py").write_text(
        'def f():\n'
        '    """See apex/amp/scaler.py."""  # apex: noqa[CITATION]: synthetic\n')
    res = run_analysis([str(pkg), str(pkg / "mod.py")],
                       root=str(tmp_path))
    assert res.files == 2, res.files
    assert len(res.suppressions_used) == 1
    assert not res.findings, "\n".join(f.render() for f in res.findings)
    # a stale noqa must surface exactly once, not once per duplicate
    (pkg / "mod.py").write_text(
        'X = 1  # apex: noqa[CITATION]: synthetic stale\n')
    res = run_analysis([str(pkg), str(pkg / "mod.py")],
                       root=str(tmp_path))
    assert [f.rule for f in res.findings] == ["NOQA-UNUSED"], \
        "\n".join(f.render() for f in res.findings)


def test_missing_target_is_a_usage_error(tmp_path):
    # a nonexistent target must be exit 2, not a 0-files "clean" exit 0
    # from the merge gate itself (the CLI's relative default targets run
    # from the wrong cwd are exactly this shape)
    with pytest.raises(ValueError, match="does not exist"):
        run_analysis([str(tmp_path / "nope")], root=str(tmp_path))
    from apex_tpu.analysis.__main__ import main
    assert main([str(tmp_path / "nope")]) == 2


def test_repo_abi_versions_parse_and_agree():
    cpp, py = parse_abi_versions(REPO)
    assert cpp is not None and py is not None and cpp == py


# --------------------------------------------------------------------------
# TRACER-LEAK
# --------------------------------------------------------------------------


_TRACER_BAD = '''
    import jax
    import numpy as np

    def leaky(x, n):
        if x > 0:            # if on tracer
            return int(x)    # coercion
        y = np.asarray(x)    # numpy on tracer
        return x.item() + n  # .item on tracer

    j = jax.jit(leaky, static_argnums=(1,))
'''

_TRACER_CLEAN = '''
    import jax
    import jax.numpy as jnp

    def fine(cfg, x, masks=None):
        if cfg:                      # static (untainted at call sites)
            x = x + 1
        if masks is not None:        # structural — is-None is static
            x = jnp.where(masks, x, 0)
        if "k" in {"k": 1}:          # key membership is structure
            pass
        b = x.shape[0]               # shape access is static
        if b > 2:
            x = x * 2
        return jnp.sum(x)

    wrap = lambda f: jax.jit(jax.shard_map(f))
    g = wrap(lambda x: fine(3, x))
'''


def test_tracer_leak_fires_on_synthetic_violations(tmp_path):
    res = _synth(tmp_path, {"pkg/mod.py": _TRACER_BAD,
                            "pkg/__init__.py": ""})
    leaks = [f for f in res.findings if f.rule == "TRACER-LEAK"]
    msgs = " | ".join(f.message for f in leaks)
    assert len(leaks) == 4, msgs
    assert "int()" in msgs and ".item()" in msgs \
        and "np.asarray" in msgs and "`if`" in msgs


def test_tracer_leak_static_escapes_stay_clean(tmp_path):
    res = _synth(tmp_path, {"pkg/mod.py": _TRACER_CLEAN,
                            "pkg/__init__.py": ""})
    assert "TRACER-LEAK" not in _rules_of(res), \
        "\n".join(f.render() for f in res.findings)


def test_tracer_leak_walks_cross_module_calls(tmp_path):
    # the jit site lives in a.py; the leak lives in the apex_tpu
    # package module it calls — the walk must cross the import
    res = _synth(tmp_path, {
        "apex_tpu/__init__.py": "",
        "apex_tpu/helper.py": '''
            def inner(cfg, v):
                if cfg:
                    return v          # cfg stays static
                return float(v)       # v is traced -> leak
        ''',
        "pkg/__init__.py": "",
        "pkg/a.py": '''
            import jax
            from apex_tpu import helper

            def entry(v):
                return helper.inner(False, v)

            j = jax.jit(entry)
        ''',
    }, targets=None)
    leaks = [f for f in res.findings if f.rule == "TRACER-LEAK"]
    assert [f.path for f in leaks] == ["apex_tpu/helper.py"], \
        "\n".join(f.render() for f in res.findings)
    assert "float()" in leaks[0].message


def test_tracer_leak_sees_aliased_jit_spellings(tmp_path):
    # `import jax as j` call sites and `from jax import jit as J`
    # decorators are the same entry point as the literal `jax.jit` —
    # modgraph shares rules/compiled.py's alias-aware jit_call_names,
    # so the two discoveries cannot drift apart again
    res = _synth(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/via_module_alias.py": '''
            import jax as j

            def f(x):
                return int(x)      # leak under j.jit

            g = j.jit(f)
        ''',
        "pkg/via_decorator_alias.py": '''
            from jax import jit as J

            @J
            def h(x):
                return float(x)    # leak under aliased decorator
        ''',
    })
    leaks = sorted(f.path for f in res.findings
                   if f.rule == "TRACER-LEAK")
    assert leaks == ["pkg/via_decorator_alias.py",
                     "pkg/via_module_alias.py"], \
        "\n".join(f.render() for f in res.findings)


# --------------------------------------------------------------------------
# USE-AFTER-DONATE
# --------------------------------------------------------------------------


_DONATE_BAD = '''
    import jax

    class Eng:
        def __init__(self):
            self._step = jax.jit(lambda c, s: (c, s),
                                 donate_argnums=(0, 1))

        def bad_read(self):
            out = self._step(self.cache, self.state)   # no rebind
            return self.cache                          # read-after
'''

_DONATE_CLEAN = '''
    import jax

    class Eng:
        def __init__(self):
            self._step = jax.jit(lambda p, c, s: (c, s),
                                 donate_argnums=(1, 2))

        def good(self):
            self.cache, self.state = self._step(
                self.params, self.cache, self.state)   # rebind-at-dispatch
            return self.cache                          # rebound: fine
'''


def test_use_after_donate_sees_jit_import_alias(tmp_path):
    # `from jax import jit as J` must be the same entry point as
    # `jax.jit` — kept consistent with modgraph's import-aware matcher
    res = _synth(tmp_path, {"pkg/mod.py": '''
        from jax import jit as J

        class Eng:
            def __init__(self):
                self._step = J(lambda c: c, donate_argnums=(0,))

            def bad(self):
                out = self._step(self.cache)   # no rebind
                return self.cache
    ''', "pkg/__init__.py": ""})
    hits = [f for f in res.findings if f.rule == "USE-AFTER-DONATE"]
    assert len(hits) == 2, "\n".join(f.render() for f in res.findings)


def test_use_after_donate_fires(tmp_path):
    res = _synth(tmp_path, {"pkg/mod.py": _DONATE_BAD,
                            "pkg/__init__.py": ""})
    hits = [f for f in res.findings if f.rule == "USE-AFTER-DONATE"]
    msgs = " ".join(f.message for f in hits)
    # 2 unrebound donations (cache, state) + 1 read-after-donate
    assert len(hits) == 3, "\n".join(f.render() for f in hits)
    assert "does not rebind" in msgs and "read before being rebound" in msgs


def test_rebind_at_dispatch_is_clean(tmp_path):
    res = _synth(tmp_path, {"pkg/mod.py": _DONATE_CLEAN,
                            "pkg/__init__.py": ""})
    assert "USE-AFTER-DONATE" not in _rules_of(res), \
        "\n".join(f.render() for f in res.findings)


# --------------------------------------------------------------------------
# RECOMPILE-HAZARD
# --------------------------------------------------------------------------


_HAZARD_BAD = '''
    import jax

    def f(x, n):
        return x

    g = jax.jit(f, static_argnums=(1,))

    def call(xs):
        return g(f"{xs}", len(xs))
'''


def test_recompile_hazard_fires(tmp_path):
    res = _synth(tmp_path, {"pkg/mod.py": _HAZARD_BAD,
                            "pkg/__init__.py": ""})
    hits = [f for f in res.findings if f.rule == "RECOMPILE-HAZARD"]
    msgs = " ".join(f.message for f in hits)
    assert len(hits) == 2, "\n".join(f.render() for f in hits)
    assert "f-string" in msgs and "len(...)" in msgs


def test_recompile_hazard_named_args_clean(tmp_path):
    res = _synth(tmp_path, {"pkg/mod.py": '''
        import jax

        def f(x, n):
            return x

        g = jax.jit(f, static_argnums=(1,))

        def call(xs, k):
            return g(xs, k)     # names, not per-call-fresh displays
    ''', "pkg/__init__.py": ""})
    assert "RECOMPILE-HAZARD" not in _rules_of(res)


# --------------------------------------------------------------------------
# PAGE-TABLE-STATIC
# --------------------------------------------------------------------------


def test_page_table_static_fires_on_request_derived_shape(tmp_path):
    res = _synth(tmp_path, {"pkg/mod.py": '''
        import numpy as np

        def admit(self, prompt, max_tokens):
            # the recompile-hazard class this rule exists for: table
            # geometry measured from the live request
            self._tables = np.zeros(
                (self.slots, len(prompt) // self.page_size), np.int32)
            pages = np.full((prompt.size // 4,), 0, np.int32)
            return pages
    ''', "pkg/__init__.py": ""})
    hits = [f for f in res.findings if f.rule == "PAGE-TABLE-STATIC"]
    msgs = "\n".join(f.render() for f in hits)
    assert len(hits) == 2, msgs
    assert any("len(...)" in f.message and "_tables" in f.message
               for f in hits), msgs
    assert any(".size" in f.message and "pages" in f.message
               for f in hits), msgs


def test_page_table_static_clean_on_config_shapes(tmp_path):
    res = _synth(tmp_path, {"pkg/mod.py": '''
        import numpy as np

        def build(self, ecfg):
            # config-derived constants: the blessed spelling
            max_pages = -(-ecfg.max_seq_len // ecfg.page_size)
            self._tables = np.full((ecfg.slots, max_pages), 0, np.int32)
            row_pages = np.zeros((max_pages,), np.int32)
            # table CONTENTS from request data are fine — tables are
            # data; only shapes are constrained
            row_pages[:len(self.shared)] = self.shared
            # non-table arrays may size from data (other rules' turf)
            buf = np.zeros((len(self.queue),), np.int32)
            return row_pages, buf
    ''', "pkg/__init__.py": ""})
    assert "PAGE-TABLE-STATIC" not in _rules_of(res), \
        "\n".join(f.render() for f in res.findings)


# --------------------------------------------------------------------------
# HOST-TIER-STATIC
# --------------------------------------------------------------------------


def test_host_tier_static_fires_on_live_derived_shape(tmp_path):
    res = _synth(tmp_path, {"pkg/mod.py": '''
        import numpy as np

        def park(self, act, payload):
            # the swap-recompile class this rule exists for: host
            # mirror geometry measured from the live conversation
            host_buf = np.zeros(
                (len(act.pages), self.page_size), np.float32)
            self._swap_rows = np.full((payload.size,), 0, np.int32)
            return host_buf
    ''', "pkg/__init__.py": ""})
    hits = [f for f in res.findings if f.rule == "HOST-TIER-STATIC"]
    msgs = "\n".join(f.render() for f in hits)
    assert len(hits) == 2, msgs
    assert any("len(...)" in f.message and "host_buf" in f.message
               for f in hits), msgs
    assert any(".size" in f.message and "_swap_rows" in f.message
               for f in hits), msgs


def test_host_tier_static_clean_on_rung_shapes(tmp_path):
    res = _synth(tmp_path, {"pkg/mod.py": '''
        import numpy as np

        def build(self, ecfg):
            # rung-derived constants: the blessed spelling
            rung = max(self.swap_rungs)
            host_buf = np.zeros((rung, ecfg.page_size), np.float32)
            spill_stage = np.empty((ecfg.lora_rank,), np.float32)
            # host-buffer CONTENTS from live data are fine — buffers
            # are data; only geometry is constrained
            host_buf[:len(self.priv)] = self.priv
            # non-host-named arrays may size from data (other rules)
            buf = np.zeros((len(self.queue),), np.int32)
            return host_buf, spill_stage, buf
    ''', "pkg/__init__.py": ""})
    assert "HOST-TIER-STATIC" not in _rules_of(res), \
        "\n".join(f.render() for f in res.findings)


# --------------------------------------------------------------------------
# WARMUP-COVERAGE
# --------------------------------------------------------------------------


_WARMUP_BAD = '''
    import jax

    class Eng:
        def __init__(self):
            self._step = jax.jit(lambda c: c)
            self._extra = jax.jit(lambda c: c)    # never warmed/tracked

        def warmup(self):
            self._step(0)

        def compiled_cache_sizes(self):
            return {"step": self._step._cache_size()}
'''


def test_warmup_coverage_fires_on_forgotten_variant(tmp_path):
    res = _synth(tmp_path, {"pkg/mod.py": _WARMUP_BAD,
                            "pkg/__init__.py": ""})
    hits = [f for f in res.findings if f.rule == "WARMUP-COVERAGE"]
    assert len(hits) == 2, "\n".join(f.render() for f in hits)
    assert all("_extra" in f.message for f in hits)


_KNOB_ENGINE = '''
    import jax

    class Eng:
        def __init__(self):
            self._step_variants = {}
            for c in (1, 2):
                self._step_variants[c] = jax.jit(lambda x: x)
            self._retire = jax.jit(lambda s: s)

        def warmup(self):
            for c, fn in sorted(self._step_variants.items()):
                fn(0)
            self._retire(0)

        def compiled_cache_sizes(self):
            out = {"retire": self._retire._cache_size()}
            for c, fn in sorted(self._step_variants.items()):
                out[f"step_c{c}"] = fn._cache_size()
            return out
'''


def test_warmup_coverage_knob_ladder_link(tmp_path):
    """The serving.tuner half: VARIANT_KNOBS entries must name a
    compiled-program dict family on a warmup-defining class — a knob
    pointing at nothing could ladder candidates warmup never compiles."""
    # positive: the declared family exists, is warmed, is tracked
    res = _synth(tmp_path, {
        "pkg/eng.py": _KNOB_ENGINE,
        "pkg/tuner.py":
            'VARIANT_KNOBS = {"decode_chunk": "_step_variants"}\n',
        "pkg/__init__.py": ""})
    assert "WARMUP-COVERAGE" not in _rules_of(res), \
        "\n".join(f.render() for f in res.findings)
    # negative: the knob maps to a family nobody builds
    (tmp_path / "bad").mkdir()
    res = _synth(tmp_path / "bad", {
        "pkg/eng.py": _KNOB_ENGINE,
        "pkg/tuner.py":
            'VARIANT_KNOBS = {"spec_k": "_missing_variants"}\n',
        "pkg/__init__.py": ""})
    hits = [f for f in res.findings if f.rule == "WARMUP-COVERAGE"]
    assert len(hits) == 1, "\n".join(f.render() for f in res.findings)
    assert "_missing_variants" in hits[0].message \
        and "'spec_k'" in hits[0].message
    assert hits[0].path == "pkg/tuner.py"
    # negative: the family exists but warmup never touches it — the
    # BASE checks fire on the engine side (the ladder link holds)
    (tmp_path / "unwarmed").mkdir()
    res = _synth(tmp_path / "unwarmed", {
        "pkg/eng.py": _KNOB_ENGINE.replace(
            """            for c, fn in sorted(self._step_variants.items()):
                fn(0)
""", ""),
        "pkg/tuner.py":
            'VARIANT_KNOBS = {"decode_chunk": "_step_variants"}\n',
        "pkg/__init__.py": ""})
    hits = [f for f in res.findings if f.rule == "WARMUP-COVERAGE"]
    assert any("_step_variants" in f.message
               and "warmup()" in f.message for f in hits), \
        "\n".join(f.render() for f in res.findings)


def test_warmup_coverage_clean_via_direct_and_getattr_refs(tmp_path):
    res = _synth(tmp_path, {"pkg/mod.py": '''
        import jax

        class Eng:
            def __init__(self):
                self._step = jax.jit(lambda c: c)
                self._admits = {}
                self._admits[(8, 1)] = jax.jit(lambda c: c)

            def warmup(self):
                self._helper()
                for k, fn in sorted(self._admits.items()):
                    fn(0)

            def _helper(self):
                self._step(0)

            def compiled_cache_sizes(self):
                out = {n: getattr(self, f"_{n}")._cache_size()
                       for n in ("step",)}
                out["admit"] = len(self._admits)
                return out
    ''', "pkg/__init__.py": ""})
    assert "WARMUP-COVERAGE" not in _rules_of(res), \
        "\n".join(f.render() for f in res.findings)


# --------------------------------------------------------------------------
# ABI-LOCKSTEP
# --------------------------------------------------------------------------


def _abi_tree(version_py):
    return {
        "csrc/host_runtime.cpp":
            "static const int32_t kAbiVersion = 3;\n",
        "apex_tpu/__init__.py": "",
        "apex_tpu/_native/__init__.py":
            f"_ABI_VERSION = {version_py}\n",
    }


def test_abi_lockstep_fires_on_drift(tmp_path):
    res = _synth(tmp_path, _abi_tree(2), targets=["apex_tpu"])
    hits = [f for f in res.findings if f.rule == "ABI-LOCKSTEP"]
    assert len(hits) == 1 and "kAbiVersion=3" in hits[0].message \
        and "_ABI_VERSION=2" in hits[0].message


def test_abi_lockstep_clean_in_lockstep(tmp_path):
    res = _synth(tmp_path, _abi_tree(3), targets=["apex_tpu"])
    assert "ABI-LOCKSTEP" not in _rules_of(res)


# --------------------------------------------------------------------------
# METRIC-DRIFT
# --------------------------------------------------------------------------


_METRIC_SRC = '''
    def wire(registry):
        registry.counter("serving_good_total", "documented")
        registry.gauge("serving_orphan_total", "not in the doc")
'''


def test_metric_drift_both_directions(tmp_path):
    res = _synth(tmp_path, {
        "apex_tpu/__init__.py": "",
        "apex_tpu/serving/__init__.py": "",
        "apex_tpu/serving/sched.py": _METRIC_SRC,
        "docs/API.md":
            "`serving_good_total` and `serving_ghost_total` exist.\n",
    }, targets=["apex_tpu"])
    hits = [f for f in res.findings if f.rule == "METRIC-DRIFT"]
    msgs = "\n".join(f.render() for f in hits)
    assert len(hits) == 2, msgs
    assert any("serving_ghost_total" in f.message
               and f.path == "docs/API.md" for f in hits), msgs
    assert any("serving_orphan_total" in f.message
               and f.path == "apex_tpu/serving/sched.py"
               for f in hits), msgs


def test_metric_drift_span_colliding_with_engine_api(tmp_path):
    # `fetch` is both an Engine method and a span-section name; a BARE
    # doc mention (`engine.fetch`) is a span claim and must be backed
    # by a registration — only the call spelling (`engine.fetch()`) is
    # excused as an API reference
    res = _synth(tmp_path, {
        "apex_tpu/__init__.py": "",
        "apex_tpu/serving/__init__.py": "",
        "apex_tpu/serving/engine.py": '''
            class Engine:
                def fetch(self):
                    pass
        ''',
        "apex_tpu/serving/sched.py": '''
            def wire(registry, spans):
                registry.counter("serving_ok_total", "")
                spans.section("engine.dispatch", 0.0, 0.0)
        ''',
        "docs/API.md": "`serving_ok_total`; `engine.dispatch` and "
                       "`engine.fetch` spans; call `engine.fetch()` "
                       "to sync.\n",
    }, targets=["apex_tpu"])
    hits = [f for f in res.findings if f.rule == "METRIC-DRIFT"]
    assert len(hits) == 1 and "engine.fetch" in hits[0].message, \
        "\n".join(f.render() for f in res.findings)


def test_metric_drift_label_and_alternation_tokens(tmp_path):
    res = _synth(tmp_path, {
        "apex_tpu/__init__.py": "",
        "apex_tpu/serving/__init__.py": "",
        "apex_tpu/serving/sched.py": '''
            def wire(registry):
                registry.counter("serving_spec_drafted_total", "")
                registry.counter("serving_spec_accepted_total", "")
                registry.counter("serving_shed_total", "", labels=("r",))
        ''',
        "docs/API.md": "`serving_spec_{drafted,accepted}_total` and "
                       '`serving_shed_total{r="x"}` are exported.\n',
    }, targets=["apex_tpu"])
    assert "METRIC-DRIFT" not in _rules_of(res), \
        "\n".join(f.render() for f in res.findings)


def test_metric_drift_slo_family_pos_and_neg(tmp_path):
    """The SLO observatory's gauge families follow the labelled-family
    shape (`serving_slo_quantile_seconds{metric="ttft",quantile="p99"}`
    in the doc) — pin that the rule accepts the documented spelling
    AND still fires on an slo-prefixed orphan/ghost pair."""
    res = _synth(tmp_path, {
        "apex_tpu/__init__.py": "",
        "apex_tpu/serving/__init__.py": "",
        "apex_tpu/serving/sched.py": '''
            def wire(registry):
                registry.gauge("serving_slo_quantile_seconds", "",
                               labels=("metric", "quantile"))
                registry.counter("serving_slo_alerts_total", "",
                                 labels=("objective", "state"))
                registry.gauge("serving_slo_orphan", "undocumented")
        ''',
        "docs/API.md":
            '`serving_slo_quantile_seconds{metric="ttft",quantile="p99"}`'
            ' and `serving_slo_alerts_total{objective="o",state="s"}` '
            'are exported, as is `serving_slo_ghost_total`.\n',
    }, targets=["apex_tpu"])
    hits = [f for f in res.findings if f.rule == "METRIC-DRIFT"]
    msgs = "\n".join(f.render() for f in hits)
    assert len(hits) == 2, msgs
    assert any("serving_slo_ghost_total" in f.message
               and f.path == "docs/API.md" for f in hits), msgs
    assert any("serving_slo_orphan" in f.message
               and f.path == "apex_tpu/serving/sched.py"
               for f in hits), msgs


# --------------------------------------------------------------------------
# EVENT-DRIFT
# --------------------------------------------------------------------------


_EVENT_VOCAB = '''
    EVENT_FIELDS = {
        "good": ("request_id",),
        "undocumented": ("n",),
        "never_recorded": ("x",),
    }
'''

_EVENT_DOC = ("#### Flight-recorder event names\n"
              "| event | fields | meaning |\n"
              "|---|---|---|\n"
              "| `good` | request_id | fine |\n"
              "| `never_recorded` | x | vocabulary orphan |\n"
              "| `phantom` | y | doc orphan |\n")


def _event_tree(tmp_path, sched_src, doc=_EVENT_DOC):
    return _synth(tmp_path, {
        "apex_tpu/__init__.py": "",
        "apex_tpu/telemetry/__init__.py": "",
        "apex_tpu/telemetry/flightrec.py": _EVENT_VOCAB,
        "apex_tpu/serving/__init__.py": "",
        "apex_tpu/serving/sched.py": sched_src,
        "docs/API.md": doc,
    }, targets=["apex_tpu"], rules=["EVENT-DRIFT"])


def test_event_drift_all_directions(tmp_path):
    res = _event_tree(tmp_path, '''
        def wire(recorder):
            recorder.record("good", "r0")
            recorder.record("undocumented", 3)
            recorder.record("ghost", 1)
            db.record("not_an_event")     # non-recorder receiver
    ''')
    hits = [f for f in res.findings if f.rule == "EVENT-DRIFT"]
    msgs = "\n".join(f.render() for f in hits)
    # ghost: recorded, not in vocabulary (anchored at the call site)
    assert any("'ghost'" in f.message
               and f.path == "apex_tpu/serving/sched.py"
               for f in hits), msgs
    # undocumented: in vocabulary + recorded, missing from the doc table
    assert any("'undocumented'" in f.message and "API.md" in f.message
               and f.path == "apex_tpu/telemetry/flightrec.py"
               for f in hits), msgs
    # never_recorded: dead vocabulary (documented but no call site)
    assert any("'never_recorded'" in f.message
               and "no record() call" in f.message for f in hits), msgs
    # phantom: documented, not in the vocabulary (anchored in the doc)
    assert any("'phantom'" in f.message and f.path == "docs/API.md"
               for f in hits), msgs
    # the non-recorder receiver stays out of scope
    assert not any("not_an_event" in f.message for f in hits), msgs
    assert len(hits) == 4, msgs


def test_event_drift_clean_tree(tmp_path):
    res = _event_tree(tmp_path, '''
        def wire(rec):
            rec.record("good", "r0")
            rec.record("undocumented", 3)
            rec.record("never_recorded", 1)
    ''', doc=("#### Flight-recorder event names\n"
              "| event | fields | meaning |\n"
              "|---|---|---|\n"
              "| `good` | request_id | fine |\n"
              "| `undocumented` | n | now documented |\n"
              "| `never_recorded` | x | recorded after all |\n"))
    assert "EVENT-DRIFT" not in _rules_of(res), \
        "\n".join(f.render() for f in res.findings)


def test_event_drift_sees_annotated_vocabulary(tmp_path):
    """The REAL flightrec module binds the vocabulary with a type
    annotation (`EVENT_FIELDS: Dict[...] = {...}` — ast.AnnAssign);
    the rule must parse that spelling too, or it is silently inert
    against the actual repo (the regression this pins: the rule
    shipped matching plain Assign only and never fired on the tree)."""
    res = _synth(tmp_path, {
        "apex_tpu/__init__.py": "",
        "apex_tpu/telemetry/__init__.py": "",
        "apex_tpu/telemetry/flightrec.py": '''
            from typing import Dict, Tuple

            EVENT_FIELDS: Dict[str, Tuple[str, ...]] = {
                "good": ("request_id",),
                "dead_entry": ("x",),
            }
        ''',
        "apex_tpu/serving/__init__.py": "",
        "apex_tpu/serving/sched.py": 'def f(recorder):\n'
                                     '    recorder.record("good", 1)\n',
        "docs/API.md": ("#### Flight-recorder event names\n"
                        "| event | fields | meaning |\n"
                        "|---|---|---|\n"
                        "| `good` | request_id | fine |\n"),
    }, targets=["apex_tpu"], rules=["EVENT-DRIFT"])
    hits = [f for f in res.findings if f.rule == "EVENT-DRIFT"]
    msgs = "\n".join(f.render() for f in hits)
    assert any("'dead_entry'" in f.message
               and "no record() call" in f.message for f in hits), msgs
    assert any("'dead_entry'" in f.message and "API.md" in f.message
               for f in hits), msgs
    assert len(hits) == 2, msgs


def test_event_drift_absent_on_foreign_trees(tmp_path):
    # no flightrec.py (or one without the vocabulary) = not this repo
    # shape; the rule must stay silent instead of flagging everything
    res = _synth(tmp_path, {
        "apex_tpu/__init__.py": "",
        "apex_tpu/serving/__init__.py": "",
        "apex_tpu/serving/sched.py": 'def f(rec):\n'
                                     '    rec.record("anything", 1)\n',
        "docs/API.md": _EVENT_DOC,
    }, targets=["apex_tpu"], rules=["EVENT-DRIFT"])
    assert "EVENT-DRIFT" not in _rules_of(res), \
        "\n".join(f.render() for f in res.findings)


def test_event_drift_slo_vocabulary_pos_and_neg(tmp_path):
    """SLO burn/alert events ride the same vocabulary contract: a
    documented + recorded `slo_state` stays clean, a recorded-but-
    unregistered `slo_ghost` fires at the call site, and a vocabulary
    entry `slo_dead` with no record() call fires as dead vocabulary."""
    res = _synth(tmp_path, {
        "apex_tpu/__init__.py": "",
        "apex_tpu/telemetry/__init__.py": "",
        "apex_tpu/telemetry/flightrec.py": '''
            EVENT_FIELDS = {
                "slo_state": ("objective", "from", "to",
                              "fast_burn", "slow_burn"),
                "slo_dead": ("x",),
            }
        ''',
        "apex_tpu/serving/__init__.py": "",
        "apex_tpu/serving/sched.py": '''
            def wire(recorder):
                recorder.record("slo_state", "o", "ok", "warning",
                                1.0, 1.0)
                recorder.record("slo_ghost", 1)
        ''',
        "docs/API.md": ("#### Flight-recorder event names\n"
                        "| event | fields | meaning |\n"
                        "|---|---|---|\n"
                        "| `slo_state` | objective, from, to, "
                        "fast_burn, slow_burn | transition |\n"
                        "| `slo_dead` | x | never recorded |\n"),
    }, targets=["apex_tpu"], rules=["EVENT-DRIFT"])
    hits = [f for f in res.findings if f.rule == "EVENT-DRIFT"]
    msgs = "\n".join(f.render() for f in hits)
    assert any("'slo_ghost'" in f.message
               and f.path == "apex_tpu/serving/sched.py"
               for f in hits), msgs
    assert any("'slo_dead'" in f.message
               and "no record() call" in f.message for f in hits), msgs
    assert not any("slo_state" in f.message for f in hits), msgs
    assert len(hits) == 2, msgs


# --------------------------------------------------------------------------
# DURABLE-WRITE
# --------------------------------------------------------------------------


def test_durable_write_fires_on_bare_artifact_writes(tmp_path):
    res = _synth(tmp_path, {"pkg/mod.py": '''
        import json
        import os

        def save(state, ckpt_dir, step):
            # the torn-artifact class this rule exists for: bare
            # open(w) at the real destination
            with open(os.path.join(ckpt_dir, f"step{step}.json"),
                      "w") as f:
                json.dump(state, f)

        def dump(report, out):
            with open(out + "/bundle.json", mode="wb") as f:
                f.write(report)

        def seal(journal_path, rows):
            f = open(journal_path, "x")
            f.write(rows)
    ''', "pkg/__init__.py": ""})
    hits = [f for f in res.findings if f.rule == "DURABLE-WRITE"]
    msgs = "\n".join(f.render() for f in hits)
    assert len(hits) == 3, msgs
    assert any("ckpt" in f.message and "'w'" in f.message
               for f in hits), msgs
    assert any("bundle" in f.message and "'wb'" in f.message
               for f in hits), msgs
    assert any("journal" in f.message and "'x'" in f.message
               for f in hits), msgs
    assert all("_atomic" in f.message for f in hits), msgs


def test_durable_write_clean_on_blessed_spellings(tmp_path):
    res = _synth(tmp_path, {"pkg/mod.py": '''
        import json
        import os

        def save(state, ckpt_dir, tmp, name):
            # writes into an atomic temp target spell the temp name,
            # not the artifact — that is the point of the idiom
            with open(os.path.join(tmp, name), "w") as f:
                json.dump(state, f)

        def read(ckpt_dir, step):
            # reads are out of scope
            with open(os.path.join(ckpt_dir, f"step{step}.json")) as f:
                return json.load(f)

        def extend(journal_path, rows):
            # appending IS the journal contract — exempt mode
            with open(journal_path, "ab") as f:
                f.write(rows)

        def scratch(workdir, payload):
            # non-durable names may write bare (other files' turf)
            with open(os.path.join(workdir, "scratch.bin"), "wb") as f:
                f.write(payload)
    ''', "pkg/__init__.py": ""})
    assert "DURABLE-WRITE" not in _rules_of(res), \
        "\n".join(f.render() for f in res.findings)


def test_durable_write_exempts_the_blessed_implementations(tmp_path):
    # _atomic.py and serving/journal.py ARE the safe paths being
    # policed — their own destination writes must not fire
    body = '''
        def write(checkpoint_path, data):
            with open(checkpoint_path, "w") as f:
                f.write(data)
    '''
    res = _synth(tmp_path, {
        "apex_tpu/__init__.py": "",
        "apex_tpu/_atomic.py": body,
        "apex_tpu/serving/__init__.py": "",
        "apex_tpu/serving/journal.py": body,
        "apex_tpu/other.py": body,
    }, targets=["apex_tpu"], rules=["DURABLE-WRITE"])
    hits = [f for f in res.findings if f.rule == "DURABLE-WRITE"]
    msgs = "\n".join(f.render() for f in hits)
    assert len(hits) == 1, msgs
    assert hits[0].path == "apex_tpu/other.py", msgs


# --------------------------------------------------------------------------
# CITATION
# --------------------------------------------------------------------------


_CITE_SRC = '''
    """Module header.

    Good: apex/amp/scaler.py (U). Wrapped but tagged:
    apex/fp16_utils/{fp16util,
    loss_scaler}.py (U). Bad, untagged: apex/contrib/foo/bar.py is
    the reference.
    """
'''


def test_citation_rule_requires_marker(tmp_path):
    res = _synth(tmp_path, {"pkg/mod.py": _CITE_SRC,
                            "pkg/__init__.py": ""})
    hits = [f for f in res.findings if f.rule == "CITATION"]
    assert len(hits) == 1, "\n".join(f.render() for f in hits)
    assert "apex/contrib/foo/bar.py" in hits[0].message


# --------------------------------------------------------------------------
# TIER1-COST
# --------------------------------------------------------------------------


_TIER1_SRC = '''
    import pytest

    def test_unmarked(engine):
        engine.warmup()          # should fire

    @pytest.mark.slow
    def test_marked(engine):
        engine.warmup()          # slow-marked: exempt

    def helper(engine):          # apex: noqa on the def line covers it
        engine.warmup()
'''


def test_tier1_cost_rule(tmp_path):
    src = _TIER1_SRC.replace(
        "def helper(engine):          # apex: noqa on the def line",
        "def helper(engine):  # apex: noqa[TIER1-COST]: shared helper")
    res = _synth(tmp_path, {"tests/test_x.py": src},
                 targets=["tests"], rules=["TIER1-COST"])
    hits = [f for f in res.findings if f.rule == "TIER1-COST"]
    assert len(hits) == 1 and "test_unmarked" in hits[0].message, \
        "\n".join(f.render() for f in res.findings)
    assert len(res.suppressions_used) == 1  # the def-line noqa


def test_tier1_cost_sees_through_lambdas(tmp_path):
    # a lambda is never scanned as a function of its own, so a warmup
    # tucked into one is charged to the enclosing def — otherwise the
    # `mk = lambda: engine.warmup()` spelling escapes the allowlist
    res = _synth(tmp_path, {"tests/test_x.py": '''
        def test_lam(engine):
            mk = lambda: engine.warmup()
            mk()
    '''}, targets=["tests"], rules=["TIER1-COST"])
    hits = [f for f in res.findings if f.rule == "TIER1-COST"]
    assert len(hits) == 1 and "test_lam" in hits[0].message, \
        "\n".join(f.render() for f in res.findings)


def test_tier1_cost_only_sees_test_files(tmp_path):
    res = _synth(tmp_path, {"pkg/mod.py": '''
        def run(engine):
            engine.warmup()
    ''', "pkg/__init__.py": ""}, rules=["TIER1-COST"])
    assert not res.findings


# --------------------------------------------------------------------------
# the suppression mechanism itself
# --------------------------------------------------------------------------


def test_justified_suppression_silences_and_counts(tmp_path):
    res = _synth(tmp_path, {"pkg/mod.py": '''
        import jax

        def f(x):
            return int(x)  # apex: noqa[TRACER-LEAK]: synthetic pin

        j = jax.jit(f)
    ''', "pkg/__init__.py": ""})
    assert not res.findings, "\n".join(f.render() for f in res.findings)
    assert len(res.suppressions_used) == 1
    s = summary_dict(res)
    assert s["suppressions"]["active"] == 1
    assert s["suppressions"]["by_rule"] == {"TRACER-LEAK": 1}


def test_bare_suppression_is_a_finding(tmp_path):
    res = _synth(tmp_path, {"pkg/mod.py": '''
        import jax

        def f(x):
            return int(x)  # apex: noqa[TRACER-LEAK]

        j = jax.jit(f)
    ''', "pkg/__init__.py": ""})
    assert _rules_of(res) == ["NOQA-BARE"], \
        "\n".join(f.render() for f in res.findings)
    assert res.exit_code == 1


def test_unused_suppression_is_a_finding(tmp_path):
    res = _synth(tmp_path, {"pkg/mod.py": '''
        def f(x):
            return x + 1  # apex: noqa[TRACER-LEAK]: nothing fires here
    ''', "pkg/__init__.py": ""})
    assert _rules_of(res) == ["NOQA-UNUSED"], \
        "\n".join(f.render() for f in res.findings)


def test_suppression_outside_targets_still_matches(tmp_path):
    # a global rule (METRIC-DRIFT) anchors findings at package files a
    # partial/--changed run never targeted; a justified suppression at
    # the registration site must silence them there too, or the
    # documented pre-commit hook exits 1 spuriously
    files = {
        "apex_tpu/__init__.py": "",
        "apex_tpu/other.py": "X = 1\n",
        "apex_tpu/serving/__init__.py": "",
        "apex_tpu/serving/sched.py": '''
            def wire(registry):
                registry.gauge("serving_internal_state", "")  # apex: noqa[METRIC-DRIFT]: internal-only, deliberately undocumented
        ''',
        "docs/API.md": "no metrics documented\n",
    }
    res = _synth(tmp_path, files, targets=["apex_tpu/other.py"])
    assert not res.findings, "\n".join(f.render() for f in res.findings)
    # the same run WITH the registration file targeted counts it active
    res2 = _synth(tmp_path, files,
                  targets=["apex_tpu/serving/sched.py"])
    assert not res2.findings, \
        "\n".join(f.render() for f in res2.findings)
    assert len(res2.suppressions_used) == 1


def test_disabled_rules_suppressions_out_of_scope(tmp_path):
    # a TIER1-COST noqa in a test file is not "unused" to a run that
    # never enabled TIER1-COST — each battery polices its own rules
    res = _synth(tmp_path, {"tests/test_x.py": '''
        def helper(engine):  # apex: noqa[TIER1-COST]: other battery
            engine.warmup()
    '''}, targets=["tests"], rules=["CITATION"])
    assert not res.findings, "\n".join(f.render() for f in res.findings)


def test_unknown_rule_suppression_is_a_finding(tmp_path):
    # a typo'd (or renamed-rule) id must not become a permanently dead
    # annotation no run ever flags — the full battery reports it; a
    # partial --rules run stays silent (it cannot tell another
    # battery's id from no such id)
    files = {"pkg/mod.py":
             "X = 1  # apex: noqa[TRACERLEAK]: typo'd id\n",
             "pkg/__init__.py": ""}
    res = _synth(tmp_path, files)
    assert _rules_of(res) == ["NOQA-UNKNOWN"], \
        "\n".join(f.render() for f in res.findings)
    assert "TRACERLEAK" in res.findings[0].message
    res2 = _synth(tmp_path, files, rules=["CITATION"])
    assert not res2.findings, \
        "\n".join(f.render() for f in res2.findings)


def test_tier1_cost_respects_pytestmark(tmp_path):
    # `pytestmark = pytest.mark.slow` at module or class level is the
    # standard whole-scope slow spelling — it must exempt exactly like
    # the per-function decorator, or authors get restyled by the linter
    res = _synth(tmp_path, {
        "tests/test_mod.py": '''
            import pytest

            pytestmark = pytest.mark.slow

            def test_soak(engine):
                engine.warmup()
        ''',
        "tests/test_cls.py": '''
            import pytest

            class TestSoak:
                pytestmark = [pytest.mark.slow]

                def test_inner(self, engine):
                    engine.warmup()

            def test_outside(engine):
                engine.warmup()   # not under the marked class: fires
        ''',
    }, targets=["tests"], rules=["TIER1-COST"])
    hits = [f for f in res.findings if f.rule == "TIER1-COST"]
    assert len(hits) == 1 and "test_outside" in hits[0].message, \
        "\n".join(f.render() for f in res.findings)


def test_docstring_noqa_examples_are_not_suppressions(tmp_path):
    res = _synth(tmp_path, {"pkg/mod.py": '''
        """Docs may show `# apex: noqa[TRACER-LEAK]: why` verbatim."""
    ''', "pkg/__init__.py": ""})
    assert not res.findings, "\n".join(f.render() for f in res.findings)


# --------------------------------------------------------------------------
# dependency hygiene
# --------------------------------------------------------------------------


def test_analysis_imports_stdlib_only(tmp_path):
    """The linter must stay importable and runnable with jax/numpy
    purged and blocked (it lints the tree BEFORE a broken change could
    even import) — same harness as serving.api's purged-import test."""
    script = tmp_path / "probe.py"
    script.write_text(textwrap.dedent(f'''
        import sys

        BLOCKED = ("jax", "jaxlib", "numpy", "scipy", "torch")

        class _Blocker:
            def find_spec(self, name, path=None, target=None):
                if name.split(".")[0] in BLOCKED:
                    raise ImportError(f"blocked import: {{name}}")

        # blocked BEFORE apex_tpu itself loads: the claim is that the
        # linter runs on a machine where jax cannot import at all (the
        # parent package degrades to its stdlib-only corners)
        sys.meta_path.insert(0, _Blocker())

        import apex_tpu
        # degradation shape: a jax-backed subpackage must surface the
        # REAL missing module, not a fake "no attribute" error...
        try:
            apex_tpu.mesh
        except ImportError as e:
            assert "jax" in str(e), e
        else:
            raise AssertionError("apex_tpu.mesh imported without jax?")
        # ...while a genuinely absent attribute stays an AttributeError
        try:
            apex_tpu.not_a_subpackage
        except AttributeError:
            pass
        from apex_tpu.analysis.core import run_analysis
        res = run_analysis(
            [{os.path.join(REPO, "apex_tpu", "analysis")!r}],
            root={REPO!r})
        print("FINDINGS", len(res.findings))
    '''))
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([sys.executable, str(script)],
                       capture_output=True, text=True, timeout=120,
                       cwd=REPO, env=env)
    assert r.returncode == 0, r.stderr
    assert "FINDINGS 0" in r.stdout, r.stdout
