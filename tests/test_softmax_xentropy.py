"""Scaled-masked softmax + fused cross-entropy kernel tests.

Oracle pattern (SURVEY.md §4): Pallas kernel vs unfused jnp reference at
fp32, per-dtype tolerances — the apex L0 compare-vs-PyTorch model.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.kernels import (
    scaled_masked_softmax,
    scaled_upper_triang_masked_softmax,
    softmax_cross_entropy,
)
from apex_tpu.transformer.enums import AttnMaskType
from apex_tpu.transformer.functional import FusedScaleMaskSoftmax

TOL = {jnp.float32: dict(rtol=1e-5, atol=1e-6),
       jnp.bfloat16: dict(rtol=2e-2, atol=2e-3)}


def _ref_softmax(x, mask, scale, causal):
    x = x.astype(jnp.float32) * scale
    if causal:
        sq, sk = x.shape[-2:]
        x = jnp.where(jnp.tril(jnp.ones((sq, sk), bool)), x, -1e30)
    if mask is not None:
        x = jnp.where(mask.astype(bool), -1e30, x)
    return jax.nn.softmax(x, axis=-1)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_scaled_masked_softmax_fwd_bwd(dtype):
    b, h, sq, sk = 2, 3, 8, 20
    x = (jax.random.normal(jax.random.PRNGKey(0), (b, h, sq, sk)) * 2).astype(dtype)
    mask = jax.random.bernoulli(jax.random.PRNGKey(1), 0.3, (b, 1, sq, sk))
    # keep at least one unmasked key per row
    mask = mask.at[..., 0].set(False)

    y = scaled_masked_softmax(x, mask, scale=0.7)
    ref = _ref_softmax(x, mask, 0.7, False)
    np.testing.assert_allclose(np.asarray(y, np.float32), np.asarray(ref),
                               **TOL[dtype])

    def loss(x):
        return jnp.sum(scaled_masked_softmax(x, mask, scale=0.7).astype(jnp.float32) ** 2)

    def loss_ref(x):
        return jnp.sum(_ref_softmax(x, mask, 0.7, False) ** 2)

    g = jax.grad(loss)(x)
    gref = jax.grad(loss_ref)(x)
    np.testing.assert_allclose(np.asarray(g, np.float32),
                               np.asarray(gref, np.float32),
                               rtol=5e-2 if dtype == jnp.bfloat16 else 1e-4,
                               atol=5e-3 if dtype == jnp.bfloat16 else 1e-5)


def test_fully_masked_row_yields_zeros():
    x = jnp.ones((1, 1, 4, 8))
    mask = jnp.ones((1, 1, 4, 8), bool)
    y = scaled_masked_softmax(x, mask)
    assert np.isfinite(np.asarray(y)).all()
    np.testing.assert_allclose(np.asarray(y), 0.0)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_causal_softmax(dtype):
    b, h, s = 2, 2, 16
    x = (jax.random.normal(jax.random.PRNGKey(2), (b, h, s, s)) * 2).astype(dtype)
    y = scaled_upper_triang_masked_softmax(x, scale=1.3)
    ref = _ref_softmax(x, None, 1.3, True)
    np.testing.assert_allclose(np.asarray(y, np.float32), np.asarray(ref),
                               **TOL[dtype])
    # strictly-upper-triangular entries are exactly zero
    upper = np.triu(np.ones((s, s), bool), 1)
    assert (np.asarray(y, np.float32)[..., upper] == 0).all()

    g = jax.grad(lambda x: jnp.sum(
        scaled_upper_triang_masked_softmax(x, scale=1.3).astype(jnp.float32) ** 2))(x)
    gref = jax.grad(lambda x: jnp.sum(_ref_softmax(x, None, 1.3, True) ** 2))(x)
    np.testing.assert_allclose(np.asarray(g, np.float32),
                               np.asarray(gref, np.float32),
                               rtol=5e-2 if dtype == jnp.bfloat16 else 1e-4,
                               atol=5e-3 if dtype == jnp.bfloat16 else 1e-5)


def test_causal_requires_square():
    with pytest.raises(ValueError):
        scaled_upper_triang_masked_softmax(jnp.ones((1, 1, 4, 8)))


def test_fused_scale_mask_softmax_dispatch():
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 2, 8, 8))
    mask = jax.random.bernoulli(jax.random.PRNGKey(4), 0.2, (2, 1, 8, 8))
    mask = mask.at[..., 0].set(False)

    fused = FusedScaleMaskSoftmax(attn_mask_type=AttnMaskType.padding, scale=0.5)
    unfused = FusedScaleMaskSoftmax(attn_mask_type=AttnMaskType.padding,
                                    scaled_masked_softmax_fusion=False, scale=0.5)
    np.testing.assert_allclose(np.asarray(fused(x, mask)),
                               np.asarray(unfused(x, mask)), rtol=1e-4, atol=1e-5)

    fc = FusedScaleMaskSoftmax(attn_mask_type=AttnMaskType.causal)
    uc = FusedScaleMaskSoftmax(attn_mask_type=AttnMaskType.causal,
                               scaled_masked_softmax_fusion=False)
    np.testing.assert_allclose(np.asarray(fc(x)), np.asarray(uc(x)),
                               rtol=1e-4, atol=1e-5)

    # causal + padding mask composes (triangle AND mask) on BOTH paths —
    # the fused branch must not silently drop causality
    pad = jnp.zeros((2, 1, 1, 8), bool).at[..., -2:].set(True)
    got = np.asarray(fc(x, pad))
    want = np.asarray(uc(x, pad))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    # row 0 attends only to col 0 (causal), and padded cols are dead
    assert np.allclose(got[..., 0, 1:], 0.0, atol=1e-6)
    assert np.allclose(got[..., -2:], 0.0, atol=1e-6)
    # non-square causal+mask is rejected, not silently misaligned (the
    # mask-less causal path already raises for sq != sk)
    with pytest.raises(ValueError, match="square"):
        fc(jax.random.normal(jax.random.PRNGKey(5), (2, 2, 1, 8)),
           jnp.zeros((2, 1, 1, 8), bool))


# -- fused cross entropy ---------------------------------------------------
def _ref_xent(logits, target, smoothing, ignore_index=-100):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
    nll = -jnp.take_along_axis(logp, jnp.clip(target, 0)[..., None], -1)[..., 0]
    loss = (1 - smoothing) * nll - smoothing * jnp.mean(logp, -1)
    return jnp.where(target == ignore_index, 0.0, loss)


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_softmax_cross_entropy(dtype, smoothing):
    b, s, v = 2, 6, 40
    logits = (jax.random.normal(jax.random.PRNGKey(5), (b, s, v)) * 3).astype(dtype)
    target = jax.random.randint(jax.random.PRNGKey(6), (b, s), 0, v)
    target = target.at[0, 0].set(-100)  # ignored token

    loss = softmax_cross_entropy(logits, target, smoothing)
    ref = _ref_xent(logits, target, smoothing)
    np.testing.assert_allclose(np.asarray(loss), np.asarray(ref),
                               rtol=2e-2 if dtype == jnp.bfloat16 else 1e-5,
                               atol=2e-2 if dtype == jnp.bfloat16 else 1e-5)
    assert float(loss[0, 0]) == 0.0

    g = jax.grad(lambda l: jnp.sum(softmax_cross_entropy(l, target, smoothing)))(logits)
    gref = jax.grad(lambda l: jnp.sum(_ref_xent(l, target, smoothing)))(logits)
    np.testing.assert_allclose(np.asarray(g, np.float32),
                               np.asarray(gref, np.float32),
                               rtol=5e-2 if dtype == jnp.bfloat16 else 1e-4,
                               atol=5e-3 if dtype == jnp.bfloat16 else 1e-5)
    np.testing.assert_allclose(np.asarray(g, np.float32)[0, 0], 0.0)


def test_xentropy_matches_smoothing_formula():
    # reference smoothed form: lse - (1-eps)x_t - eps*mean(x)
    v = 16
    logits = jax.random.normal(jax.random.PRNGKey(7), (5, v))
    target = jax.random.randint(jax.random.PRNGKey(8), (5,), 0, v)
    eps = 0.2
    loss = softmax_cross_entropy(logits, target, eps)
    lse = jax.scipy.special.logsumexp(logits, -1)
    xt = jnp.take_along_axis(logits, target[:, None], -1)[:, 0]
    manual = lse - (1 - eps) * xt - eps * jnp.mean(logits, -1)
    np.testing.assert_allclose(np.asarray(loss), np.asarray(manual), rtol=1e-5)


def test_scaled_masked_softmax_broadcast_masks():
    """generic variant (U) [era]: padding masks broadcasting over query
    (and head/batch) dims must work and equal the expanded-mask result."""
    import jax

    from apex_tpu.kernels import (
        generic_scaled_masked_softmax,
        scaled_masked_softmax,
    )

    x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 8, 16))
    pad = jax.random.bernoulli(jax.random.PRNGKey(1), 0.3, (2, 1, 1, 16))
    full = jnp.broadcast_to(pad, x.shape)
    got = generic_scaled_masked_softmax(x, pad, scale=0.5)
    want = scaled_masked_softmax(x, full, scale=0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-7)
    # legacy [b, sq, sk] head-broadcast form keeps working
    m3 = jax.random.bernoulli(jax.random.PRNGKey(2), 0.3, (2, 8, 16))
    got3 = scaled_masked_softmax(x, m3)
    want3 = scaled_masked_softmax(x, jnp.broadcast_to(m3[:, None], x.shape))
    np.testing.assert_allclose(np.asarray(got3), np.asarray(want3),
                               rtol=1e-6, atol=1e-7)
