"""Host-swap oversubscription oracles (serving.hostswap).

Oracle pattern (SURVEY.md §4): a conversation that parked to host RAM
mid-stream — or was preempted for a higher-priority tenant and later
replayed — must emit BIT-identical tokens (greedy AND sampled rows
alike) to the same request served uninterrupted; the baseline side is
the plain paged engine, itself pinned bit-identical to contiguous and
to solo ``gpt.generate`` by the paged-cache and serving suites, so the
chain grounds out at the solo oracle. Swap churn must never move the
recompile sentinel (every swap-batch rung is a warmup-compiled
variant), preemption decisions must re-derive from a post-mortem
bundle's recorded candidates (``replay_preemptions``), and the same
LRU mechanism pages cold LoRA adapter rows to host — registrations
past the static pool stream identically to an all-resident pool.

Pure-host units (rung planner, LRU index, tier capacity eviction,
allocator host-tier counters) run device-free up top.
"""

import dataclasses

import jax
import pytest

from apex_tpu import mesh as mx
from apex_tpu.models import gpt
from apex_tpu.serving import Request, SamplingParams
from apex_tpu.serving.engine import Engine, EngineConfig
from apex_tpu.serving.hostswap import (
    HostPageTier, LRUIndex, plan_rungs, swap_rungs)
from apex_tpu.serving.pages import PageAllocator
from apex_tpu.serving.scheduler import Scheduler
from apex_tpu.telemetry.flightrec import FlightRecorder, read_bundle
from apex_tpu.telemetry.replay import replay_preemptions
from apex_tpu.transformer.testing import standalone_gpt_config

VOCAB = 96


def _cfg(**overrides):
    base = dict(vocab_size=VOCAB, seq_len=64)
    base.update(overrides)
    return standalone_gpt_config(**base)


_PARAMS = {}


def params_of(cfg):
    # one shared init — parameters are storage-kind independent
    if "p" not in _PARAMS:
        base = dataclasses.replace(cfg, kv_cache_dtype="auto")
        _PARAMS["p"] = gpt.init(base, jax.random.PRNGKey(0))
    return _PARAMS["p"]


def _mk_engine(cfg, ecfg, mesh, fault_plan=None):  # apex: noqa[TIER1-COST]: shared tiny-engine builder — one warm-cache warmup per host-swap variant serves every test below
    return Engine(cfg, params_of(cfg), mesh, ecfg,
                  fault_plan=fault_plan).warmup()


# paged base + the host tier on top; resume_policy per test
_ECFG = EngineConfig(slots=3, max_prompt_len=16, max_seq_len=32,
                     decode_chunk=2, prompt_buckets=(8, 16),
                     admit_batch_sizes=(1, 2), page_size=8,
                     host_swap=True)


def _trace(n=5, mt=12, tenants=None, adapters=0):
    reqs = []
    for i in range(n):
        p_len = 1 + (7 * i + 3) % 14
        prompt = [int(t) for t in jax.random.randint(
            jax.random.PRNGKey(50 + i), (p_len,), 0, VOCAB)]
        sp = (SamplingParams(temperature=0.9, top_k=20, seed=i)
              if i % 2 else SamplingParams())
        reqs.append(Request(
            f"r{i}", prompt, max_tokens=mt, sampling=sp,
            tenant=tenants[i % len(tenants)] if tenants else "default",
            adapter=(i % (adapters + 1)) if adapters else 0))
    return reqs


def _run(engine, reqs, **kw):
    sched = Scheduler(engine, **kw)
    for r in reqs:
        sched.submit(r)
    sched.run_until_idle()
    return ({rid: c.tokens for rid, c in sched.completions.items()},
            sched.summary())


def _run_paused(engine, reqs, pause_after=2, resume_after=2, **kw):
    """The park-mid-stream drive: a few ticks in, pause every active
    conversation, keep serving, resume them all, drain."""
    sched = Scheduler(engine, **kw)
    for r in reqs:
        sched.submit(r)
    for _ in range(pause_after):
        sched.step()
    paused = [rid for rid in sorted(a.request.request_id
                                    for a in sched.active.values())
              if sched.pause(rid)]
    assert paused, "nothing was mid-stream to pause — trace too short"
    for _ in range(resume_after):
        sched.step()
    for rid in paused:
        assert sched.resume(rid)
    sched.run_until_idle()
    return ({rid: c.tokens for rid, c in sched.completions.items()},
            sched.summary(), sched)


# -- the swap-batch rung planner (pure host) ---------------------------------


def test_swap_rung_planner():
    assert swap_rungs(1) == (1,)
    assert swap_rungs(4) == (1, 2, 4)
    # powers of two only — binary decomposition covers everything in
    # between without a padding page ever travelling
    assert swap_rungs(6) == (1, 2, 4)
    for n in range(1, 40):
        plan = plan_rungs(n)
        assert sum(plan) == n
        assert plan == sorted(plan, reverse=True)  # largest first
        rungs = set(swap_rungs(n))
        assert all(r in rungs for r in plan), (n, plan)
    assert plan_rungs(5) == [4, 1]  # the binary decomposition
    assert plan_rungs(0) == []  # nothing to move
    with pytest.raises(ValueError):
        plan_rungs(-1)
    with pytest.raises(ValueError):
        swap_rungs(0)


def test_lru_index():
    lru = LRUIndex()
    for k in ("a", "b", "c"):
        lru.touch(k)
    assert list(lru) == ["a", "b", "c"]  # coldest first
    lru.touch("a")  # refresh: a becomes hottest
    assert lru.pop_coldest() == "b"
    assert lru.pop_coldest(pinned={"c"}) == "a"  # pinned survives
    lru.discard("zz")  # absent discard is a no-op
    lru.discard("c")
    assert lru.pop_coldest() is None


def test_host_tier_capacity_eviction():
    tier = HostPageTier(capacity_pages=4)
    assert tier.park("a", "pay-a", 2, 100) == []
    assert tier.park("b", "pay-b", 2, 100) == []
    # over capacity: the COLDEST entry spills out of the tier (its
    # conversation silently downgrades to recompute-resume)
    evicted = tier.park("c", "pay-c", 2, 100)
    assert [k for k, _ in evicted] == ["a"]
    assert "a" not in tier and "b" in tier
    # touch refreshes recency, so the next eviction picks c, not b
    tier.touch("b")
    assert [k for k, _ in tier.park("d", "pay-d", 2, 100)] == ["c"]
    ent = tier.take("b")
    assert ent.payload == "pay-b" and ent.n_pages == 2
    assert tier.take("b") is None  # taken is gone
    with pytest.raises(ValueError):
        tier.park("d", "again", 1, 1)  # re-park is a bug
    s = tier.stats()
    assert s["parks_total"] == 4.0 and s["drops_total"] == 2.0
    assert s["takes_total"] == 1.0 and s["parked_entries"] == 1.0


def test_page_allocator_host_tier_counters():
    a = PageAllocator(num_pages=9, page_size=8)
    a.note_swap_out(3, 300)
    a.note_swap_out(2, 200)
    a.note_swap_in(3, 300)   # scatter-back resume
    a.note_swap_drop(2, 200)  # capacity eviction / recompute-resume
    s = a.stats()
    assert s["pages_swapped"] == 0.0 and s["swap_bytes"] == 0.0
    # cumulative traffic counts PAGES moved, and a drop is not an in
    assert s["swap_outs_total"] == 5.0 and s["swap_ins_total"] == 3.0
    a.note_swap_out(4, 400)
    assert a.stats()["pages_swapped"] == 4.0
    assert a.stats()["swap_bytes"] == 400.0
    # reset() rebuilds the DEVICE pool (fault recovery) — parked host
    # payloads stay valid (they were copied out), so the host-tier
    # occupancy and traffic counters must survive the rebuild
    a.reset()
    s = a.stats()
    assert s["pages_swapped"] == 4.0 and s["swap_outs_total"] == 9.0


# -- park/resume stream parity (the oversubscription oracle) -----------------


@pytest.mark.parametrize("policy", ["swap", "recompute", "auto"])
def test_pause_resume_stream_parity(devices8, policy):
    """A conversation parked to host RAM mid-stream and resumed —
    scatter-back, replay-from-snapshot, or the auto-priced choice —
    emits BIT-identical tokens (greedy and sampled rows alike) to the
    same trace served uninterrupted, the recompile sentinel never
    moves (every swap rung is a warmed variant), and the resume-path
    counters attribute the policy taken."""
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    eng = _mk_engine(_cfg(), dataclasses.replace(
        _ECFG, resume_policy=policy), mesh)
    try:
        base, _ = _run(eng, _trace())
        sen0 = eng.recompile_sentinel()
        toks, summ, _ = _run_paused(eng, _trace())
        assert toks == base
        assert eng.recompile_sentinel() == sen0, "swap churn recompiled"
        assert summ["pauses"] >= 1.0
        if policy == "swap":
            assert summ["swap_resumes"] >= 1.0
            assert summ["recompute_resumes"] == 0.0
        elif policy == "recompute":
            assert summ["recompute_resumes"] >= 1.0
            assert summ["swap_resumes"] == 0.0
        else:  # auto resolves to SOME resume path, bit-identically
            assert summ["swap_resumes"] + summ["recompute_resumes"] \
                >= 1.0
        assert summ["parked_conversations"] == 0.0  # all came back
        assert summ["pages_in_use"] == 0.0
    finally:
        eng.close()


@pytest.mark.parametrize("kind", [
    "lora", "spec",
    # the int8 composition is the paged suite's int8 stream-parity arm
    # composed with the (auto-covered) swap plumbing — slow tier
    # (tier-1 budget offset for the host-swap suite)
    pytest.param("int8", marks=pytest.mark.slow)])
def test_pause_resume_composed_parity(devices8, kind):
    """Park/resume stays bit-identical composed with the other cache
    tenants of the page pool: quantized KV storage, batched per-slot
    LoRA adapters, and speculative decode (drafter history parks and
    resumes with the slot row)."""
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    cfg = _cfg() if kind != "int8" else dataclasses.replace(
        _cfg(), kv_cache_dtype="int8")
    ecfg = dataclasses.replace(_ECFG, resume_policy="swap")
    adapters = 0
    if kind == "lora":
        ecfg = dataclasses.replace(ecfg, adapter_slots=3,
                                   adapter_rank=4, adapter_alpha=8.0)
        adapters = 2
    elif kind == "spec":
        ecfg = dataclasses.replace(ecfg, spec_k=2, spec_hist=12)
    eng = _mk_engine(cfg, ecfg, mesh)
    try:
        for i in range(adapters):
            eng.register_adapter(seed=70 + i)
        base, _ = _run(eng, _trace(adapters=adapters))
        # pause after ONE step: a spec wave emits up to
        # decode_chunk * (spec_k + 1) tokens per step, so later pauses
        # can find the whole trace already finished
        toks, summ, _ = _run_paused(eng, _trace(adapters=adapters),
                                    pause_after=1)
        assert toks == base
        assert summ["swap_resumes"] >= 1.0
    finally:
        eng.close()


def test_recompile_guard_flat_over_swap_churn(devices8):
    """Many park/resume cycles across varying page counts and both
    resume paths never trace a new program — the armed recompile
    guard's oversubscription extension."""
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    eng = _mk_engine(_cfg(), dataclasses.replace(
        _ECFG, resume_policy="auto"), mesh)
    try:
        sen0 = eng.recompile_sentinel()
        base, _ = _run(eng, _trace())
        for rnd in range(3):
            toks, _, _ = _run_paused(eng, _trace(),
                                     pause_after=1 + rnd)
            assert toks == base, f"round {rnd} drift"
        assert eng.recompile_sentinel() == sen0
    finally:
        eng.close()


# -- host-tier capacity pressure (engine level) ------------------------------


def test_host_tier_pressure_downgrades_to_recompute(devices8):
    """A bounded host tier (``host_swap_pages``) evicts the coldest
    parked payload under parking pressure; the evicted conversation
    still resumes bit-identically through the replay snapshot, and
    the scheduler counts the capacity drop."""
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    # room for ~one parked conversation's pages — parking a wave of
    # three MUST spill the coldest payloads
    eng = _mk_engine(_cfg(), dataclasses.replace(
        _ECFG, resume_policy="swap", host_swap_pages=3), mesh)
    try:
        base, _ = _run(eng, _trace())
        toks, summ, _ = _run_paused(eng, _trace())
        assert toks == base
        assert summ["swap_capacity_drops"] >= 1.0
        assert summ["recompute_resumes"] >= 1.0  # the evicted ones
        assert summ["swap_resumes"] >= 1.0       # the retained one
    finally:
        eng.close()


# -- preemption: the scheduler evicts pages, replay restores the stream ------


def _preempt_run(devices8, tmp_path=None):
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    # ample-pool baseline: the same trace, nobody preempted
    eng = _mk_engine(_cfg(), dataclasses.replace(
        _ECFG, resume_policy="auto"), mesh)
    try:
        base, _ = _run(eng, _trace(tenants=("t0", "t1", "t2")))
    finally:
        eng.close()
    # starved pool: 5 pages (one sink + two 2-page conversations) for
    # three tenants — admission pressure MUST preempt
    eng = _mk_engine(_cfg(), dataclasses.replace(
        _ECFG, resume_policy="auto", num_pages=5), mesh)
    rec = FlightRecorder()
    try:
        sched = Scheduler(eng, recorder=rec, preempt=True)
        for r in _trace(tenants=("t0", "t1", "t2")):
            sched.submit(r)
        sched.run_until_idle()
        toks = {rid: c.tokens for rid, c in sched.completions.items()}
        summ = sched.summary()
        reasons = {rid: c.finish_reason
                   for rid, c in sched.completions.items()}
        bundle = None
        if tmp_path is not None:
            bundle = sched.dump_bundle("test",
                                       bundle_dir=str(tmp_path))
    finally:
        eng.close()
    evs = [e for e in rec.to_dicts(rec.events())
           if e["event"] == "preempt"]
    return base, toks, summ, reasons, evs, bundle


def test_preempt_replay_stream_parity(devices8, tmp_path):
    """Under ``PagesExhausted`` pressure the scheduler preempts the
    WFQ-largest tenant's pages and later replays the victim through
    the fault-replay machinery: every stream (greedy and sampled)
    stays bit-identical to the unstarved run, victims finish with
    their natural reasons (never ``error``), preempt events carry the
    full recorded candidate map, and the whole decision sequence
    re-derives from the post-mortem bundle with zero mismatches —
    while a tampered victim is flagged."""
    base, toks, summ, reasons, evs, bundle = _preempt_run(
        devices8, tmp_path)
    assert toks == base
    assert all(r in ("stop", "length", "eos") for r in reasons.values()), \
        reasons
    assert summ["preemptions"] >= 1.0
    assert len(evs) == int(summ["preemptions"])
    for e in evs:
        assert e["candidates"] and e["tenant"] in e["candidates"]
        assert e["service"] == e["candidates"][e["tenant"]]
    # the bundle is the decision record: replay re-derives every
    # victim from the recorded WFQ candidates
    b = read_bundle(bundle)
    out = replay_preemptions(b)
    assert out is not None and "skipped" not in out
    assert out["preemptions"] == len(evs)
    assert out["mismatches"] == []
    assert out["readmitted"] == out["preemptions"]
    # tamper: a re-written victim must not re-derive
    for e in b["events.jsonl"]:
        if e.get("event") == "preempt":
            e["tenant"] = "nobody"
    bad = replay_preemptions(b)
    assert bad["mismatches"], "tampered preempt victim not flagged"
    # gate: a bundle from a non-host-swap engine has nothing to replay
    b2 = read_bundle(bundle)
    b2["config.json"]["engine"]["engine"]["host_swap"] = False
    assert replay_preemptions(b2) is None


# -- adapter paging: hundreds registered, a static pool resident -------------


def test_adapter_paging_stream_parity(devices8):
    """With the host tier on, ``register_adapter`` past the static
    pool spills cold adapters' rows to host instead of refusing: a
    pool of 2 usable rows serving 4 registered adapters emits the
    SAME streams as an all-resident pool (same seeds), and the
    spill/page-in counters show the LRU actually paged."""
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    seeds = [70, 71, 72, 73]
    lora = dict(adapter_rank=4, adapter_alpha=8.0,
                resume_policy="swap")

    def run_pool(slots):
        eng = _mk_engine(_cfg(), dataclasses.replace(
            _ECFG, adapter_slots=slots, **lora), mesh)
        try:
            for s in seeds:
                eng.register_adapter(seed=s)
            toks, _ = _run(eng, _trace(n=8, adapters=len(seeds)))
            stats = eng.adapter_paging_stats()
        finally:
            eng.close()
        return toks, stats

    resident, _ = run_pool(slots=len(seeds) + 1)  # everything fits
    paged, stats = run_pool(slots=3)              # 2 usable rows
    assert paged == resident
    assert stats["registered"] == float(len(seeds))
    assert stats["rows"] < stats["registered"] + 1
    assert stats["spills_total"] >= 1.0
    assert stats["pageins_total"] >= 1.0


def test_adapter_register_hard_cap_without_host_tier(devices8):
    """Without the host tier the static pool is still a hard cap —
    the paging escape hatch must not silently change the contract for
    engines that did not opt in."""
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    eng = _mk_engine(_cfg(), dataclasses.replace(
        _ECFG, host_swap=False, adapter_slots=2, adapter_rank=4,
        adapter_alpha=8.0), mesh)
    try:
        eng.register_adapter(seed=70)
        with pytest.raises(ValueError):
            eng.register_adapter(seed=71)
    finally:
        eng.close()
