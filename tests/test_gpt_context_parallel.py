"""GPT with context parallelism: ring-attention training path.

Oracle: the cp-sharded model computes the same loss/gradients as the
unsharded model (same params, same tokens) — sequence sharding is a
layout, not a numerics change.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu import mesh as mx
from apex_tpu.amp import ScalerConfig
from apex_tpu.models import gpt, training
from apex_tpu.optimizers import fused_adam

CFG = dict(vocab_size=96, hidden_size=32, num_layers=2, num_heads=2,
           seq_len=32, remat=False, compute_dtype=jnp.float32)


def _data():
    tok = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 96)
    return tok, jnp.roll(tok, -1, 1)


def test_cp_loss_matches_unsharded():
    cfg0 = gpt.GPTConfig(**CFG)
    cfg_cp = gpt.GPTConfig(context_parallel=True, **CFG)
    params = jax.jit(lambda k: gpt.init(cfg0, k))(jax.random.PRNGKey(0))
    tok, tgt = _data()
    pspec = gpt.param_specs(cfg0)

    mesh1 = mx.build_mesh(tp=1, devices=jax.devices()[:1])
    base = jax.jit(jax.shard_map(
        lambda p: gpt.loss(cfg0, p, tok, tgt), mesh=mesh1,
        in_specs=(pspec,), out_specs=P(), check_vma=False))(params)

    mesh = mx.build_mesh(tp=1, cp=4, dp=1, devices=jax.devices()[:4])
    cp_loss = jax.jit(jax.shard_map(
        lambda p: jax.lax.pmean(
            gpt.loss(cfg_cp, p, tok, tgt), "cp"),
        mesh=mesh, in_specs=(pspec,), out_specs=P(), check_vma=False))(
            params)
    np.testing.assert_allclose(float(cp_loss), float(base), rtol=2e-5)


def test_cp_grads_match_unsharded():
    cfg0 = gpt.GPTConfig(**CFG)
    cfg_cp = gpt.GPTConfig(context_parallel=True, **CFG)
    params = jax.jit(lambda k: gpt.init(cfg0, k))(jax.random.PRNGKey(0))
    tok, tgt = _data()
    pspec = gpt.param_specs(cfg0)

    mesh1 = mx.build_mesh(tp=1, devices=jax.devices()[:1])
    g_base = jax.jit(jax.shard_map(
        lambda p: jax.grad(lambda pp: gpt.loss(cfg0, pp, tok, tgt))(p),
        mesh=mesh1, in_specs=(pspec,), out_specs=pspec,
        check_vma=False))(params)

    mesh = mx.build_mesh(tp=1, cp=4, dp=1, devices=jax.devices()[:4])
    g_cp = jax.jit(jax.shard_map(
        lambda p: jax.lax.pmean(
            jax.grad(lambda pp: gpt.loss(cfg_cp, pp, tok, tgt))(p), "cp"),
        mesh=mesh, in_specs=(pspec,), out_specs=pspec,
        check_vma=False))(params)
    for a, b in zip(jax.tree.leaves(g_base), jax.tree.leaves(g_cp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=1e-5)


def test_cp_train_step_with_tp():
    """Full train step on a tp=2 x cp=2 x dp=2 mesh: loss decreases."""
    cfg = gpt.GPTConfig(context_parallel=True, sequence_parallel=False,
                        **CFG)
    mesh = mx.build_mesh(tp=2, cp=2, devices=jax.devices()[:8])
    init_fn, step_fn = training.make_train_step(
        cfg, mesh, fused_adam(1e-2), ScalerConfig(enabled=False))
    state = init_fn(jax.random.PRNGKey(0))
    tok, tgt = _data()
    losses = []
    for _ in range(4):
        state, m = step_fn(state, tok, tgt)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]


def test_cp_composes_with_pp():
    """CP × PP pipeline loss == unsharded loss (pins the 'composes with
    PP' claim: the pipeline chunk stream runs on cp-local seq shards)."""
    cfg0 = gpt.GPTConfig(**CFG)
    cfg_cp = gpt.GPTConfig(context_parallel=True, **CFG)
    params = jax.jit(lambda k: gpt.init(cfg0, k))(jax.random.PRNGKey(0))
    tok, tgt = _data()
    pspec = gpt.param_specs(cfg0)

    mesh1 = mx.build_mesh(tp=1, devices=jax.devices()[:1])
    base = jax.jit(jax.shard_map(
        lambda p: gpt.loss(cfg0, p, tok, tgt), mesh=mesh1,
        in_specs=(pspec,), out_specs=P(), check_vma=False))(params)

    mesh = mx.build_mesh(tp=1, pp=2, cp=2, dp=1,
                         devices=jax.devices()[:4])
    pp_params = gpt.interleave_layers(params, CFG["num_layers"], 2)
    pspec_pp = gpt.param_specs(cfg0, pipeline=True)
    got = jax.jit(jax.shard_map(
        lambda p: jax.lax.pmean(
            gpt.pipeline_loss(cfg_cp, p, tok, tgt, n_micro=2), "cp"),
        mesh=mesh, in_specs=(pspec_pp,), out_specs=P(),
        check_vma=False))(pp_params)
    np.testing.assert_allclose(float(got), float(base), rtol=2e-5)


def test_cp_with_sp_rejected():
    cfg = gpt.GPTConfig(context_parallel=True, sequence_parallel=True,
                        **CFG)
    mesh = mx.build_mesh(tp=2, cp=2, devices=jax.devices()[:8])
    init_fn, step_fn = training.make_train_step(
        cfg, mesh, fused_adam(1e-2), ScalerConfig(enabled=False))
    state = init_fn(jax.random.PRNGKey(0))
    tok, tgt = _data()
    import pytest
    with pytest.raises(ValueError, match="sequence"):
        step_fn(state, tok, tgt)


def test_cp_zigzag_loss_matches_unsharded():
    """cp_zigzag: the balanced chunk assignment is a permutation of the
    sequence — the (token-mean) loss equals the unsharded model's."""
    cfg0 = gpt.GPTConfig(**CFG)
    cfg_z = gpt.GPTConfig(context_parallel=True, cp_zigzag=True, **CFG)
    params = jax.jit(lambda k: gpt.init(cfg0, k))(jax.random.PRNGKey(0))
    tok, tgt = _data()
    pspec = gpt.param_specs(cfg0)

    mesh1 = mx.build_mesh(tp=1, devices=jax.devices()[:1])
    base = jax.jit(jax.shard_map(
        lambda p: gpt.loss(cfg0, p, tok, tgt), mesh=mesh1,
        in_specs=(pspec,), out_specs=P(), check_vma=False))(params)

    mesh = mx.build_mesh(tp=1, cp=4, dp=1, devices=jax.devices()[:4])
    z_loss = jax.jit(jax.shard_map(
        lambda p: jax.lax.pmean(gpt.loss(cfg_z, p, tok, tgt), "cp"),
        mesh=mesh, in_specs=(pspec,), out_specs=P(), check_vma=False))(
            params)
    np.testing.assert_allclose(float(z_loss), float(base), rtol=2e-5)


def test_cp_zigzag_train_step_matches_contiguous():
    """One full train step under zigzag == contiguous cp (same params,
    same data): gradients are permutation-invariant."""
    from apex_tpu.optimizers import fused_sgd

    tok, tgt = _data()
    outs = {}
    for name, zig in (("contig", False), ("zigzag", True)):
        cfg = gpt.GPTConfig(context_parallel=True, cp_zigzag=zig, **CFG)
        mesh = mx.build_mesh(tp=1, cp=4, dp=1, devices=jax.devices()[:4])
        init_fn, step_fn = training.make_train_step(
            cfg, mesh, fused_sgd(0.1), ScalerConfig(enabled=False))
        state = init_fn(jax.random.PRNGKey(0))
        state, m = step_fn(state, tok, tgt)
        outs[name] = (float(m["loss"]), jax.device_get(state.params))
    np.testing.assert_allclose(outs["contig"][0], outs["zigzag"][0],
                               rtol=2e-5)
    for a, b in zip(jax.tree.leaves(outs["contig"][1]),
                    jax.tree.leaves(outs["zigzag"][1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)
