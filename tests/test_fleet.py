"""apex_tpu.serving.fleet — the fleet-resilience suite.

Headline oracle (the PR-12 acceptance pin): with a seeded per-replica
fault plan that terminally fails one of two replicas mid-burst, every
client stream completes BIT-IDENTICAL to a clean single-replica run of
the same trace (zero duplicate, zero lost tokens — the router fails
interrupted requests over with their emitted-prefix snapshots and the
target replica re-derives + suppresses), the fleet ``/healthz`` never
leaves 200 while at least one replica is ``ok``, a drain → rebuild →
re-admit rolling-restart cycle completes with zero shed requests, the
failed replica auto-dumps a post-mortem bundle referenced by the fleet
incident manifest, and the recompile guard stays flat per replica
through all of it.

Also here: the multi-engine recompile-sentinel regression (a second
live engine's compiles must never be attributed to the first engine's
armed guard — the hard prerequisite the router would otherwise trip)
and the Engine/Router context-manager contract.
"""

import collections
import json
import os

import jax
import pytest

from apex_tpu import mesh as mx
from apex_tpu.models import gpt
from apex_tpu.serving import Request, SamplingParams
from apex_tpu.serving.engine import Engine, EngineConfig
from apex_tpu.serving.fleet import (
    REPLICA_COOLING,
    REPLICA_FAILED,
    REPLICA_LIVE,
    FleetConfig,
    Router,
)
from apex_tpu.serving.request import FINISH_ERROR
from apex_tpu.serving.resilience import (
    EngineFailed,
    FaultPlan,
    FaultSpec,
    FleetFaultPlan,
    ResilienceConfig,
)
from apex_tpu.serving.scheduler import QueueFull, Scheduler
from apex_tpu.telemetry import FlightRecorder, Registry

VOCAB = 96


@pytest.fixture(scope="module")
def model(devices8):
    from apex_tpu.transformer.testing import standalone_gpt_config

    cfg = standalone_gpt_config(vocab_size=VOCAB, seq_len=64)
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    return cfg, params, mesh


def _mk_sched(model, plan=None, *, slots=2, retries=8, **sched_kw):  # apex: noqa[TIER1-COST]: shared tiny-replica builder — one warm-cache warmup per replica serves every fleet test below
    cfg, params, mesh = model
    eng = Engine(cfg, params, mesh,
                 EngineConfig(slots=slots, max_prompt_len=8,
                              max_seq_len=24, decode_chunk=2),
                 fault_plan=plan).warmup()
    # watchdog generous: on a throttled host a >30s chunk would trip
    # the router's breaker and evict the kill drill's victim before
    # its dispatch indices are consumed (the fleet SURVIVES either
    # way, but the drill tests assert the terminal outcome)
    sched_kw.setdefault("resilience", ResilienceConfig(
        max_retries=retries, backoff_base_s=0.001,
        watchdog_timeout_s=600.0))
    return Scheduler(eng, **sched_kw)


def _reqs(n, *, seed0=7000, max_tokens=6):
    """Deterministic mixed trace (greedy + seeded-sampled) — exactly
    the per-request determinism failover bit-exactness rests on."""
    out = []
    for i in range(n):
        p_len = 2 + (3 * i) % 6
        prompt = [int(t) for t in jax.random.randint(
            jax.random.PRNGKey(seed0 + i), (p_len,), 0, VOCAB)]
        sp = (SamplingParams(temperature=0.9, top_k=7, seed=seed0 + i)
              if i % 2 else SamplingParams())
        out.append(Request(f"f{seed0}_{i}", prompt,
                           max_tokens=max_tokens, sampling=sp))
    return out


@pytest.fixture(scope="module")
def ref_sched(model):
    """ONE clean single-replica scheduler shared by every oracle
    reference run (request ids are unique per trace seed, so traces
    stack on it without collision) — a module-level engine instead of
    one warmup per test."""
    sched = _mk_sched(model)
    yield sched
    sched.engine.close()


def _clean_reference(ref, reqs):
    """The oracle: the same trace through the clean replica."""
    for r in reqs:
        ref.submit(r)
    ref.run_until_idle()
    return {r.request_id: ref.completions[r.request_id].tokens
            for r in reqs}


def _drive_collecting(router):
    """Run the fleet to idle, collecting per-request streamed tokens
    and sampling the fleet healthz every tick."""
    streamed = collections.defaultdict(list)
    statuses = []
    while not router.idle():
        router.step()
        statuses.append(router.health.healthz()[0])
        for ev in router.pop_events():
            if ev.token is not None:
                streamed[ev.request_id].append(ev.token)
        router._maybe_sleep()
    return streamed, statuses


# --- unit coverage (host-only, fast) ----------------------------------------


def test_fleet_fault_plan_kill_random_and_validation():
    plans = FleetFaultPlan.kill(1, 3, at=5, rebuilds=2)
    assert len(plans) == 3
    assert not plans[0].specs and not plans[2].specs
    assert [s.index for s in plans[1].specs] == [5, 6]
    assert all(s.point == "dispatch" and s.kind == "error"
               for s in plans[1].specs)
    assert "r1=" in plans.describe()
    with pytest.raises(ValueError, match="outside fleet"):
        FleetFaultPlan.kill(3, 3)
    with pytest.raises(ValueError, match="at least one replica"):
        FleetFaultPlan([])
    # seeded randoms: derived per replica, bit-reproducible
    a = FleetFaultPlan.random(11, 2, n_faults=2)
    b = FleetFaultPlan.random(11, 2, n_faults=2)
    assert [p.specs for p in a] == [p.specs for p in b]
    assert a[0].specs != a[1].specs
    a[0].take("admit")
    a.reset()
    assert a[0].counts()["admit"] == 0 and not a.injected


def test_fleet_config_validation():
    with pytest.raises(ValueError, match="breaker_guard_alarms"):
        FleetConfig(breaker_guard_alarms=0)
    with pytest.raises(ValueError, match="max_failovers"):
        FleetConfig(max_failovers=0)
    with pytest.raises(ValueError, match="cooldown"):
        FleetConfig(breaker_cooldown_steps=0)


def test_router_constructor_validation(model):
    s0 = _mk_sched(model)
    s1 = _mk_sched(model)
    try:
        with pytest.raises(ValueError, match="at least one replica"):
            Router([])
        with pytest.raises(ValueError, match="distinct"):
            Router([s0, s0])
        r = Router([s0, s1])
        with pytest.raises(ValueError, match="exactly one router"):
            Router([s0, s1])  # already owned
        r.close()
    finally:
        s0.engine.close()
        s1.engine.close()


# --- routing + parity -------------------------------------------------------


def test_router_routes_and_streams_match_single_replica(model, ref_sched):
    """Clean-fleet oracle: requests spread over 2 replicas, merged
    completions + streams bit-identical to the single-replica run,
    fleet metrics/summary consistent."""
    reqs = _reqs(8, seed0=7100)
    want = _clean_reference(ref_sched, reqs)
    registry = Registry()
    rec = FlightRecorder()
    with Router([_mk_sched(model), _mk_sched(model)],
                registry=registry, recorder=rec) as router:
        for r in reqs:
            router.submit(r)
        streamed, statuses = _drive_collecting(router)
        assert len(router.completions) == len(reqs)
        for r in reqs:
            comp = router.completions[r.request_id]
            assert comp.tokens == want[r.request_id], r.request_id
            assert streamed[r.request_id] == comp.tokens
        assert set(statuses) == {200}
        s = router.summary()
        assert s["routed"] == len(reqs)
        assert s["failover_waves"] == 0 and s["aborted_requests"] == 0
        # both replicas actually served (health-weighted spreading)
        assert all(rep.routed > 0 for rep in router.replicas)
        routed = registry.counter("serving_fleet_routed_total",
                                  labels=("replica",))
        assert sum(c.value for c in routed.children()) == len(reqs)
        assert any(e[2] == "route" for e in rec.events())
        # duplicate ids rejected fleet-wide
        with pytest.raises(ValueError, match="duplicate"):
            router.submit(reqs[0])


# --- THE acceptance pin: kill one replica mid-burst -------------------------


def test_kill_one_replica_mid_burst_streams_bit_identical(model, ref_sched, tmp_path):
    """Replica 1 terminally fails mid-burst (seeded FleetFaultPlan):
    every stream completes bit-identical to the clean run, the fleet
    /healthz never leaves 200 (replica 0 stays ok), the victim
    auto-dumps a post-mortem bundle, the fleet incident manifest links
    it, and both replicas' recompile guards stay flat throughout."""
    reqs = _reqs(8, seed0=7200)
    want = _clean_reference(ref_sched, reqs)
    plans = FleetFaultPlan.kill(1, 2, at=2)
    rec = FlightRecorder()
    bundle_dir = str(tmp_path / "incidents")
    scheds = [_mk_sched(model, plans[i], bundle_dir=bundle_dir,
                        recorder=rec)
              for i in range(2)]
    guards = [s.engine.recompile_guard() for s in scheds]
    for g in guards:
        g.__enter__()
    with Router(scheds, recorder=rec, bundle_dir=bundle_dir) as router:
        for r in reqs:
            router.submit(r)
        streamed, statuses = _drive_collecting(router)
        # the victim died terminally; the fleet never stopped serving
        assert scheds[1].health.state == "failed"
        assert router.replicas[1].state == REPLICA_FAILED
        assert set(statuses) == {200}, "fleet /healthz left 200"
        # zero duplicate, zero lost tokens: streams == completions ==
        # the clean single-replica oracle
        assert len(router.completions) == len(reqs)
        for r in reqs:
            comp = router.completions[r.request_id]
            assert comp.finish_reason != FINISH_ERROR, r.request_id
            assert comp.tokens == want[r.request_id], r.request_id
            assert streamed[r.request_id] == comp.tokens, r.request_id
        s = router.summary()
        assert s["failover_waves"] >= 1
        assert s["failed_over_requests"] >= 1
        # the victim's own black box fired...
        victim_bundles = scheds[1].bundles_written
        assert victim_bundles, "failed replica dumped no bundle"
        # ...and the fleet incident manifest links it
        assert len(router.incidents_written) == 1
        with open(os.path.join(router.incidents_written[0],
                               "manifest.json")) as f:
            manifest = json.load(f)
        assert manifest["replica"] == 1
        assert manifest["replica_bundles"] == victim_bundles
        assert set(manifest["evicted_request_ids"]) <= {
            r.request_id for r in reqs}
        # the router's flight recorder saw the failover decisions
        names = [e[2] for e in rec.events()]
        assert "failover" in names and "route" in names
    # recompile guard flat per replica: rebuilds, eviction, terminal
    # failure, and failover replays never compiled anything
    for g in guards:
        g.__exit__(None, None, None)
        assert not g.tripped, g.alarms
    for sc in scheds:
        sc.engine.close()


def test_retry_exhaustion_fails_over_instead_of_erroring(model, ref_sched):
    """A request whose bounded retries exhaust on one replica is
    handed to another replica and COMPLETES with its exact stream —
    the single-engine error outcome becomes a fleet hand-off."""
    reqs = _reqs(4, seed0=7300)
    want = _clean_reference(ref_sched, reqs)
    # three consecutive dispatch faults: attempts 1..3 > max_retries=2
    # exhausts on the third, below max_consecutive_rebuilds+1=4 so the
    # replica survives degraded (no terminal failure)
    plan = FaultPlan([FaultSpec("dispatch", i, "error")
                      for i in (1, 2, 3)])
    scheds = [_mk_sched(model, plan if i == 1 else None, retries=2)
              for i in range(2)]
    with Router(scheds) as router:
        for r in reqs:
            router.submit(r)
        router.run_until_idle()
        assert scheds[1].health.state != "failed"
        assert len(router.completions) == len(reqs)
        for r in reqs:
            comp = router.completions[r.request_id]
            assert comp.finish_reason != FINISH_ERROR, r.request_id
            assert comp.tokens == want[r.request_id], r.request_id
        assert router.summary()["failed_over_requests"] >= 1
        assert scheds[1].summary()["retry_exhausted"] >= 1


def test_breaker_trips_on_watchdog_evicts_and_cools(model, ref_sched):
    """Watchdog trips cross the breaker threshold: the replica's work
    fails over, it leaves rotation (cooling), rejoins after the
    cooldown, and every stream still completes bit-identically."""
    reqs = _reqs(6, seed0=7400)
    want = _clean_reference(ref_sched, reqs)
    # replica 1 flags EVERY chunk as hung (timeout 0) — deterministic
    scheds = [
        _mk_sched(model),
        _mk_sched(model, resilience=ResilienceConfig(
            max_retries=8, backoff_base_s=0.001,
            watchdog_timeout_s=0.0)),
    ]
    cfg = FleetConfig(breaker_watchdog_trips=2,
                      breaker_cooldown_steps=5)
    with Router(scheds, config=cfg) as router:
        for r in reqs:
            router.submit(r)
        saw_cooling = False
        while not router.idle():
            router.step()
            saw_cooling |= (router.replicas[1].state
                            == REPLICA_COOLING)
            router._maybe_sleep()
        assert saw_cooling, "breaker never opened"
        # a cooling replica counts as pending fleet work: idle() must
        # hold ticks coming until the cooldown re-admits it (an
        # idle-gated driver would otherwise strand it out of rotation
        # forever — the all-cooling fleet would 429 every submit)
        while router.replicas[1].state == REPLICA_COOLING:
            assert not router.idle(), \
                "idle() released the driver mid-cooldown"
            router.step()
        assert router.replicas[1].state == REPLICA_LIVE
        assert len(router.completions) == len(reqs)
        for r in reqs:
            assert router.completions[r.request_id].tokens \
                == want[r.request_id], r.request_id
        assert router.summary()["failover_waves"] >= 1


# --- drain-for-rolling-restart ----------------------------------------------


def test_drain_rebuild_readmit_zero_shed(model, ref_sched):
    """The rolling-restart primitive: drain a replica mid-burst, let
    its in-flight requests finish on it, rebuild, re-admit — zero
    requests shed or errored, streams bit-identical, the rest of the
    fleet kept serving throughout."""
    reqs = _reqs(10, seed0=7500)
    want = _clean_reference(ref_sched, reqs)
    rec = FlightRecorder()
    with Router([_mk_sched(model), _mk_sched(model)],
                recorder=rec) as router:
        for r in reqs:
            router.submit(r)
        for _ in range(2):
            router.step()
        router.drain(1)
        assert router.replicas[1].state == REPLICA_LIVE
        router.run_until_idle()
        assert len(router.completions) == len(reqs)
        for r in reqs:
            comp = router.completions[r.request_id]
            assert comp.finish_reason != FINISH_ERROR
            assert comp.tokens == want[r.request_id], r.request_id
        assert router.summary()["drains"] == 1
        shed = sum(sc.summary()["shed"] for sc in
                   (rep.sched for rep in router.replicas))
        assert shed == 0
        phases = [e[3][1] for e in rec.events() if e[2] == "drain"]
        assert phases == ["begin", "idle", "rebuilt", "readmit"]
        # draining replica rejoined rotation for real
        router.submit(Request("after_drain", [3, 5], max_tokens=3))
        router.run_until_idle()
        assert "after_drain" in router.completions


def test_restart_replaces_failed_replica_from_factory(model):
    """After a terminal failure, restart(i) builds a fresh replica
    from the factory, re-admits it, and it serves again."""
    plans = FleetFaultPlan.kill(1, 2, at=1)
    built = []

    def factory(i):
        s = _mk_sched(model)
        built.append(i)
        return s

    scheds = [_mk_sched(model, plans[i]) for i in range(2)]
    with Router(scheds, factory=factory) as router:
        for r in _reqs(6, seed0=7600):
            router.submit(r)
        router.run_until_idle()
        assert router.replicas[1].state == REPLICA_FAILED
        with pytest.raises(ValueError, match="terminally failed"):
            router.drain(1)
        router.restart(1)
        assert built == [1]
        assert router.replicas[1].state == REPLICA_LIVE
        assert router.replicas[1].routable()
        # the fresh replica takes traffic
        router.submit(Request("post_restart", [2, 4, 6], max_tokens=3))
        router.run_until_idle()
        assert "post_restart" in router.completions
        assert router.summary()["restarts"] == 1


# --- fleet overload + terminal mapping --------------------------------------


def test_fleet_queue_full_and_engine_failed(model):
    s0 = _mk_sched(model, max_queue=2)
    s1 = _mk_sched(model, max_queue=2)
    with Router([s0, s1]) as router:
        assert router.can_accept(4)
        assert not router.can_accept(5)
        for r in _reqs(4, seed0=7700):
            router.submit(r)
        with pytest.raises(QueueFull) as ei:
            router.submit(Request("overflow", [1, 2], max_tokens=2))
        assert ei.value.retry_after_s >= 0.0
        assert router.summary()["queue_full"] == 1.0
        router.run_until_idle()
        # whole fleet terminal -> EngineFailed, the 503 mapping
        for rep in router.replicas:
            rep.sched.health.fail("test")
        router.step()
        assert router.health.healthz()[0] == 503
        assert not router.can_accept(1)
        with pytest.raises(EngineFailed):
            router.submit(Request("dead", [1], max_tokens=1))


# --- multi-engine recompile sentinel (the satellite regression) -------------


def test_second_live_engine_not_attributed_to_first_guard(model):
    """The router prerequisite: engine B's construction + warmup +
    serving compiles while engine A's guard is armed must NOT trip A —
    compile events attribute by tracked-cache ownership, and only
    unclaimed process-wide strays alarm every guard."""
    import numpy as np

    a = _mk_sched(model).engine
    sent_a = a.recompile_sentinel()
    with a.recompile_guard() as g:
        # a second live engine: constructed, warmed, and served while
        # A's guard is armed
        b = _mk_sched(model).engine
        sent_b = b.recompile_sentinel()
        b.admit(0, [1, 2, 3], 4)
        b.step()
        assert g.check() == {}, "B's compiles leaked into A's guard"
    assert not g.tripped, g.alarms
    # B's own sentinel tracked its programs (claim-based attribution)
    assert all(v == 1 for v in
               sent_b.compiles_total()["tracked"].values())
    # an untracked stray compile is still a process-wide hazard: BOTH
    # engines' guards see it
    from apex_tpu.telemetry.recompile import RecompileError

    with pytest.raises(RecompileError):
        with a.recompile_guard():
            jax.jit(lambda x: x * 3.5)(np.arange(5.0))
    assert sent_a.compiles_total()["attributed"] >= 1
    b.close()
    a.close()


# --- context managers (the close() footgun satellite) -----------------------


def test_engine_and_router_context_managers(model):
    cfg, params, mesh = model
    with Engine(cfg, params, mesh,
                EngineConfig(slots=1, max_prompt_len=8,
                             max_seq_len=24)) as eng:
        sent = eng.recompile_sentinel()
        assert eng._sentinel is sent
    assert eng._sentinel is None  # close() ran on exit
    s0, s1 = _mk_sched(model), _mk_sched(model)
    with Router([s0, s1]) as router:
        assert router.engine is s0.engine
    assert s0.on_evict is None and s1.on_evict is None
    assert s0.engine._sentinel is None


# --- seeded fleet chaos soak (slow) + its tier-1 smoke ----------------------


def _chaos_fleet_run(model, ref, seed, n_reqs, kill_at):
    """One seeded kill-one-replica soak: random per-replica faults on
    top of the deterministic replica-1 kill."""
    reqs = _reqs(n_reqs, seed0=9000 + seed)
    want = _clean_reference(ref, reqs)
    kill = FleetFaultPlan.kill(1, 2, at=kill_at)
    noise = FleetFaultPlan.random(seed, 2, n_faults=2,
                                  points=("fetch",), max_index=30)
    # replica 0 gets the random noise (recoverable), replica 1 the
    # kill — both deterministic, the soak replays exactly
    plans = [noise[0], kill[1]]
    scheds = [_mk_sched(model, plans[i]) for i in range(2)]
    with Router(scheds) as router:
        for r in reqs:
            router.submit(r)
        streamed, statuses = _drive_collecting(router)
        assert len(router.completions) == n_reqs
        drift = [r.request_id for r in reqs
                 if router.completions[r.request_id].tokens
                 != want[r.request_id]
                 or streamed[r.request_id]
                 != router.completions[r.request_id].tokens]
        errored = [rid for rid, c in router.completions.items()
                   if c.finish_reason == FINISH_ERROR]
        assert not drift, f"seed {seed}: stream drift {drift}"
        assert not errored, f"seed {seed}: errored {errored}"
        assert 200 in statuses
        return router.summary()


def test_fleet_chaos_smoke(model, ref_sched):
    """Tier-1 slice of the soak: one seed, kill + one random fetch
    fault, bit-exact streams."""
    s = _chaos_fleet_run(model, ref_sched, seed=3, n_reqs=6, kill_at=3)
    assert s["failover_waves"] >= 1


@pytest.mark.slow
def test_fleet_chaos_soak_randomized(model, ref_sched):
    """The replayable fleet soak: several seeds, every stream
    bit-identical to its clean run despite a replica death plus
    random recoverable faults on the survivor."""
    for seed in (1, 2, 5):
        _chaos_fleet_run(model, ref_sched, seed=seed, n_reqs=10, kill_at=2)
