"""contrib multihead_attn / conv fusions / groupbn + profiler subsystem.

Oracle pattern (SURVEY.md §4): fused block vs unfused jnp reference at
fp32, per-dtype tolerances.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import profiler
from apex_tpu.contrib import (
    conv_bias_relu,
    encdec_attn,
    group_batch_norm_nhwc,
    init_encdec_attn,
    init_self_attn,
    self_attn,
)
from apex_tpu.contrib.conv_bias_relu import conv_frozen_scale_bias_relu


def _ref_attention(q, k, v, causal=False):
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) / d ** 0.5
    if causal:
        sq = q.shape[2]
        mask = jnp.tril(jnp.ones((sq, sq), bool))
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v)


def _ref_self_attn(params, x, num_heads, causal=False):
    qkv = jnp.einsum("sbh,hk->sbk", x, params["qkv"]["kernel"])
    qkv = qkv + params["qkv"]["bias"]
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def heads(t):
        s, b, h = t.shape
        return jnp.transpose(
            t.reshape(s, b, num_heads, h // num_heads), (1, 2, 0, 3))

    o = _ref_attention(heads(q), heads(k), heads(v), causal)
    b, n, s, d = o.shape
    o = jnp.transpose(o, (2, 0, 1, 3)).reshape(s, b, n * d)
    return jnp.einsum("sbh,hk->sbk", o, params["out"]["kernel"]) + params[
        "out"]["bias"]


@pytest.mark.parametrize("causal", [False, True])
def test_self_attn_matches_reference(causal):
    key = jax.random.PRNGKey(0)
    p = init_self_attn(key, 64)
    x = jax.random.normal(jax.random.PRNGKey(1), (16, 2, 64))
    got = self_attn(p, x, 4, causal=causal)
    want = _ref_self_attn(p, x, 4, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_self_attn_norm_add_residual():
    p = init_self_attn(jax.random.PRNGKey(0), 64, include_norm_add=True)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 2, 64))
    y = self_attn(p, x, 4, include_norm_add=True)
    assert y.shape == x.shape
    # zeroing the out-projection must reduce the block to identity
    p0 = {**p, "out": {"kernel": jnp.zeros_like(p["out"]["kernel"]),
                       "bias": jnp.zeros_like(p["out"]["bias"])}}
    np.testing.assert_allclose(
        np.asarray(self_attn(p0, x, 4, include_norm_add=True)),
        np.asarray(x), rtol=1e-6, atol=1e-6)


def test_self_attn_prob_dropout_semantics():
    """Dropout hits the attention probabilities (apex semantics), so with
    p→0 the result converges to the no-dropout path and with rng=None
    dropout is off entirely."""
    p = init_self_attn(jax.random.PRNGKey(0), 64)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 2, 64))
    base = self_attn(p, x, 4)
    off = self_attn(p, x, 4, dropout_p=0.5, rng=None)
    np.testing.assert_allclose(np.asarray(off), np.asarray(base),
                               rtol=1e-5, atol=1e-5)
    tiny = self_attn(p, x, 4, dropout_p=1e-7, rng=jax.random.PRNGKey(2))
    np.testing.assert_allclose(np.asarray(tiny), np.asarray(base),
                               rtol=1e-3, atol=1e-3)
    # with real dropout the output changes and stays finite
    drop = self_attn(p, x, 4, dropout_p=0.5, rng=jax.random.PRNGKey(2))
    assert np.isfinite(np.asarray(drop)).all()
    assert float(jnp.abs(drop - base).max()) > 1e-3


def test_encdec_attn_shapes_and_memory_lengths():
    p = init_encdec_attn(jax.random.PRNGKey(0), 64)
    q = jax.random.normal(jax.random.PRNGKey(1), (6, 2, 64))
    mem = jax.random.normal(jax.random.PRNGKey(2), (12, 2, 64))
    y = encdec_attn(p, q, mem, 4)
    assert y.shape == q.shape
    # masking all-but-first memory position == attending to 1-length memory
    lens = jnp.array([1, 1], jnp.int32)
    y_masked = encdec_attn(p, q, mem, 4, key_padding_lens=lens)
    y_trunc = encdec_attn(p, q, mem[:1], 4)
    np.testing.assert_allclose(np.asarray(y_masked), np.asarray(y_trunc),
                               rtol=1e-4, atol=1e-4)


def test_conv_bias_relu_fusions():
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8, 3))
    w = jax.random.normal(jax.random.PRNGKey(1), (3, 3, 3, 5)) * 0.1
    b = jnp.linspace(-1, 1, 5)
    from jax import lax
    ref = lax.conv_general_dilated(
        x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")) + b
    np.testing.assert_allclose(
        np.asarray(conv_bias_relu(x, w, b)),
        np.asarray(jnp.maximum(ref, 0)), rtol=1e-5, atol=1e-5)
    scale = jnp.full((5,), 2.0)
    np.testing.assert_allclose(
        np.asarray(conv_frozen_scale_bias_relu(x, w, scale, b)),
        np.asarray(jnp.maximum((ref - b) * 2.0 + b, 0)),
        rtol=1e-5, atol=1e-5)


def test_group_batch_norm_nhwc_local_stats():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 6, 6, 3)) * 3 + 1
    scale = jnp.ones((3,))
    bias = jnp.zeros((3,))
    rm = jnp.zeros((3,))
    rv = jnp.ones((3,))
    y, nm, nv = group_batch_norm_nhwc(x, scale, bias, rm, rv, axis=None)
    # normalised output has ~zero mean / unit variance per channel
    np.testing.assert_allclose(np.asarray(y.mean((0, 1, 2))), 0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(y.std((0, 1, 2))), 1, atol=1e-3)
    # running stats moved toward the batch stats
    assert float(jnp.abs(nm - 0.1 * x.mean((0, 1, 2))).max()) < 1e-5
    # fused add+relu epilogue
    z = -jnp.ones_like(x) * 10.0
    y2, _, _ = group_batch_norm_nhwc(x, scale, bias, rm, rv, axis=None,
                                     z=z, relu=True)
    assert float(y2.min()) == 0.0


def test_group_batch_norm_cross_replica(devices8=None):
    from jax.sharding import PartitionSpec as P

    from apex_tpu import mesh as mx
    mesh = mx.build_mesh(tp=1, devices=jax.devices()[:8])
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 4, 4, 3))
    scale = jnp.ones((3,)); bias = jnp.zeros((3,))
    rm = jnp.zeros((3,)); rv = jnp.ones((3,))

    def local(xl):
        y, nm, nv = group_batch_norm_nhwc(xl, scale, bias, rm, rv, axis="dp")
        return y, nm, nv
    y, nm, nv = jax.jit(jax.shard_map(
        local, mesh=mesh, in_specs=(P("dp"),),
        out_specs=(P("dp"), P(), P()), check_vma=False))(x)
    # group stats == global batch stats
    _, nm_ref, _ = group_batch_norm_nhwc(x, scale, bias, rm, rv, axis=None)
    np.testing.assert_allclose(np.asarray(nm), np.asarray(nm_ref),
                               rtol=1e-5, atol=1e-6)


def test_step_timer_and_metrics(tmp_path):
    timer = profiler.StepTimer(tokens_per_step=100, window=10)
    x = jnp.arange(4.0)
    timer.tick(x)
    for _ in range(3):
        timer.tick(x * 2)
    s = timer.summary()
    assert s["steps"] == 3 and s["tokens_per_sec"] > 0
    assert profiler.model_flops_per_token(100, remat=True) == 800.0

    log = profiler.MetricsLogger(jsonl_path=str(tmp_path / "m.jsonl"))
    log.log(0, {"loss": jnp.float32(3.5), "lr": 0.1})
    log.log(1, {"loss": jnp.float32(3.2), "lr": 0.1})
    log.close()
    import json
    lines = [json.loads(l) for l in open(tmp_path / "m.jsonl")]
    assert lines[1]["loss"] == pytest.approx(3.2)
    assert log.history[0]["step"] == 0


def test_metrics_tensorboard_sink(tmp_path):
    """The optional TensorBoard sink writes real event files when the
    (gated) writer import succeeds — live in this image via torch."""
    pytest.importorskip("torch.utils.tensorboard")
    tb_dir = str(tmp_path / "tb")
    log = profiler.MetricsLogger(tensorboard_dir=tb_dir)
    assert log._tb is not None
    log.log(0, {"loss": jnp.float32(3.5)})
    log.log(1, {"loss": jnp.float32(3.2)})
    log.close()
    import glob
    import os
    events = glob.glob(tb_dir + "/events.out.tfevents.*")
    assert events and os.path.getsize(events[0]) > 0


def test_latency_stats_ring_wraparound():
    """The O(1) ring buffer keeps exactly the most recent ``capacity``
    samples across wraparound — same summary() contract as the list
    window it replaced (count = lifetime total, stats over the window),
    and an empty accumulator summarises to {}."""
    stats = profiler.LatencyStats(capacity=4)
    assert stats.summary() == {}
    stats.add(5.0)  # partially-filled window
    s = stats.summary()
    assert s["count"] == 1.0 and s["mean_ms"] == 5000.0
    assert s["p50_ms"] == 5000.0 and s["max_ms"] == 5000.0
    # wrap twice: samples 1..10 at capacity 4 retain {7, 8, 9, 10}
    stats = profiler.LatencyStats(capacity=4)
    for i in range(1, 11):
        stats.add(float(i))
    s = stats.summary()
    assert s["count"] == 10.0
    assert s["mean_ms"] == 8500.0          # mean(7..10) in ms
    assert s["max_ms"] == 10000.0          # 5s and 6s evicted
    assert s["p50_ms"] == 8500.0
    assert s["p99_ms"] <= s["max_ms"]


def test_step_timer_window_is_ring(tmp_path):
    """StepTimer windows through the shared O(1) ring: the window caps
    at ``window`` retaining the most recent ticks, reset clears, and
    publish() mirrors the summary into registry gauges."""
    from apex_tpu.telemetry import Registry
    from apex_tpu.telemetry.ring import Ring

    timer = profiler.StepTimer(tokens_per_step=10, window=3)
    assert isinstance(timer._times, Ring)
    for _ in range(6):
        timer.tick()
    s = timer.summary()
    assert s["steps"] == 3.0  # window kept the most recent 3 of 5
    assert timer._times.total == 5 and timer._times.dropped == 2
    reg = Registry()
    pub = timer.publish(reg)
    assert pub == s
    text = reg.to_prometheus_text()
    assert "train_steps 3" in text
    assert "train_tokens_per_sec" in text
    timer.reset()
    assert timer.summary() == {}


def test_metrics_logger_ring_ctx_and_registry(tmp_path):
    """MetricsLogger: O(1) ring history with the oldest dropped at
    capacity, context-manager close, registry gauge mirroring with
    sanitized names — and the JSONL line format byte-stable."""
    import json

    from apex_tpu.telemetry import Registry

    reg = Registry()
    jsonl = str(tmp_path / "m.jsonl")
    with profiler.MetricsLogger(jsonl_path=jsonl, history=2,
                                registry=reg) as log:
        for i in range(4):
            log.log(i, {"loss": 4.0 - i, "grad_norm/global": 0.5})
    assert log._jsonl.closed
    # ring: most recent 2 of 4, oldest first
    assert [h["step"] for h in log.history] == [2, 3]
    # registry view: last value wins, name sanitized to a legal metric
    assert reg.gauge("loss").value == 1.0
    assert reg.gauge("grad_norm_global").value == 0.5
    assert reg.gauge("step").value == 3.0
    # byte-stable JSONL: same keys, same order, plain floats
    lines = open(jsonl).read().splitlines()
    assert json.loads(lines[0]) == {"loss": 4.0,
                                    "grad_norm/global": 0.5, "step": 0}
    assert lines[0] == json.dumps({"loss": 4.0, "grad_norm/global": 0.5,
                                   "step": 0})


def test_annotate_and_sync():
    with profiler.annotate("test-range"):
        y = jnp.sum(jnp.arange(10.0))
    profiler._sync(y)
    assert float(y) == 45.0


def test_op_profile_self_times(tmp_path):
    """op_profile parses a trace capture into nested-aware self-times:
    a while containing two fusions self-times to its remainder, and
    category/source attribution survives aggregation."""
    import gzip
    import json
    import os

    d = tmp_path / "plugins" / "profile" / "2026_01_01_00_00_00"
    os.makedirs(d)
    events = [
        {"ph": "M", "pid": 3, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 3, "tid": 1, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        # host-side event must be ignored
        {"ph": "M", "pid": 9, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "M", "pid": 9, "tid": 1, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        {"ph": "X", "pid": 9, "tid": 1, "name": "hostjunk",
         "ts": 0, "dur": 999},
        # while.1 [0, 100) containing fusion.1 [10, 40) and fusion.2
        # [50, 90) -> self 30
        {"ph": "X", "pid": 3, "tid": 1, "name": "while.1", "ts": 0,
         "dur": 100, "args": {"hlo_category": "while"}},
        {"ph": "X", "pid": 3, "tid": 1, "name": "fusion.1", "ts": 10,
         "dur": 30, "args": {"hlo_category": "convolution fusion",
                             "source": "model.py:42"}},
        {"ph": "X", "pid": 3, "tid": 1, "name": "fusion.2", "ts": 50,
         "dur": 40, "args": {"hlo_category": "loop fusion"}},
        # top-level copy after the while
        {"ph": "X", "pid": 3, "tid": 1, "name": "copy.1", "ts": 120,
         "dur": 10, "args": {"hlo_category": "data formatting",
                             "source": "model.py:99"}},
    ]
    with gzip.open(d / "vm.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)

    prof = profiler.op_profile(str(tmp_path))
    by_name = {o["name"]: o for o in prof["top_ops"]}
    assert by_name["while.1"]["seconds"] == pytest.approx(30e-6)
    assert by_name["fusion.1"]["seconds"] == pytest.approx(30e-6)
    assert by_name["fusion.2"]["seconds"] == pytest.approx(40e-6)
    assert by_name["copy.1"]["seconds"] == pytest.approx(10e-6)
    assert "hostjunk" not in by_name
    assert prof["total_s"] == pytest.approx(110e-6)
    assert prof["by_category"]["data formatting"] == pytest.approx(10e-6)
    assert by_name["fusion.1"]["source"] == "model.py:42"
    assert by_name["fusion.1"]["count"] == 1


def test_op_profile_missing_trace(tmp_path):
    with pytest.raises(FileNotFoundError, match="trace.json.gz"):
        profiler.op_profile(str(tmp_path))


def test_op_profile_newest_capture_and_nested_streams(tmp_path):
    """Two capture dirs under one logdir: op_profile parses the newest
    (by mtime); its fixture nests ops on BOTH cores, so per-stream
    self-time accounting and category rollup are exercised together."""
    import gzip
    import json
    import os
    import time

    def write(dirname, events):
        d = tmp_path / "plugins" / "profile" / dirname
        os.makedirs(d)
        path = d / "vm.trace.json.gz"
        with gzip.open(path, "wt") as f:
            json.dump({"traceEvents": events}, f)
        return path

    meta = []
    for pid in (3, 4):
        meta += [
            {"ph": "M", "pid": pid, "name": "process_name",
             "args": {"name": f"/device:TPU:{pid - 3}"}},
            {"ph": "M", "pid": pid, "tid": 1, "name": "thread_name",
             "args": {"name": "XLA Ops"}},
        ]
    write("2026_01_01_00_00_00", meta + [
        {"ph": "X", "pid": 3, "tid": 1, "name": "stale.1", "ts": 0,
         "dur": 50, "args": {"hlo_category": "loop fusion"}}])
    time.sleep(0.05)  # distinct mtimes
    # newest capture: a while on each core, each containing one fusion
    newest = write("2026_01_01_00_00_59", meta + [
        {"ph": "X", "pid": 3, "tid": 1, "name": "while.a", "ts": 0,
         "dur": 100, "args": {"hlo_category": "while"}},
        {"ph": "X", "pid": 3, "tid": 1, "name": "fusion.a", "ts": 20,
         "dur": 30, "args": {"hlo_category": "loop fusion"}},
        {"ph": "X", "pid": 4, "tid": 1, "name": "while.b", "ts": 10,
         "dur": 60, "args": {"hlo_category": "while"}},
        {"ph": "X", "pid": 4, "tid": 1, "name": "fusion.b", "ts": 30,
         "dur": 20, "args": {"hlo_category": "convolution fusion"}},
    ])
    prof = profiler.op_profile(str(tmp_path))
    assert prof["trace_path"] == str(newest)
    by_name = {o["name"]: o for o in prof["top_ops"]}
    assert "stale.1" not in by_name
    # self-time = parent minus its own core's child only
    assert by_name["while.a"]["seconds"] == pytest.approx(70e-6)
    assert by_name["while.b"]["seconds"] == pytest.approx(40e-6)
    assert prof["total_s"] == pytest.approx(160e-6)
    assert prof["by_category"]["while"] == pytest.approx(110e-6)
    assert prof["by_category"]["loop fusion"] == pytest.approx(30e-6)
    assert prof["by_category"]["convolution fusion"] == \
        pytest.approx(20e-6)


def test_op_profile_multi_device_streams(tmp_path):
    """Concurrent ops on different cores must NOT nest: each (pid, tid)
    stream gets its own stack, so overlapping-in-time ops on two devices
    keep their full self-times."""
    import gzip
    import json
    import os

    d = tmp_path / "plugins" / "profile" / "2026_01_01_00_00_01"
    os.makedirs(d)
    events = []
    for pid in (3, 4):
        events += [
            {"ph": "M", "pid": pid, "name": "process_name",
             "args": {"name": f"/device:TPU:{pid - 3}"}},
            {"ph": "M", "pid": pid, "tid": 1, "name": "thread_name",
             "args": {"name": "XLA Ops"}},
        ]
    # core0 op [0, 100) and core1 op [10, 40) overlap in wall time
    events += [
        {"ph": "X", "pid": 3, "tid": 1, "name": "fusion.a", "ts": 0,
         "dur": 100, "args": {"hlo_category": "loop fusion"}},
        {"ph": "X", "pid": 4, "tid": 1, "name": "fusion.b", "ts": 10,
         "dur": 30, "args": {"hlo_category": "loop fusion"}},
    ]
    with gzip.open(d / "vm.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)
    prof = profiler.op_profile(str(tmp_path))
    by_name = {o["name"]: o for o in prof["top_ops"]}
    assert by_name["fusion.a"]["seconds"] == pytest.approx(100e-6)
    assert by_name["fusion.b"]["seconds"] == pytest.approx(30e-6)
    assert prof["total_s"] == pytest.approx(130e-6)
