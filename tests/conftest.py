"""Test backbone: simulate an 8-device mesh on CPU.

Apex emulates multi-node topology by spawning one NCCL process per local GPU
(apex/transformer/testing/distributed_test_base.py (U)). On the XLA side we
do strictly better (SURVEY.md §4): force the host platform to expose 8
virtual CPU devices and run every distributed test single-process on a real
``jax.sharding.Mesh``. Must run before any jax backend is initialised.
"""

import os

import re

_flags = os.environ.get("XLA_FLAGS", "")
_m = re.search(r"--xla_force_host_platform_device_count=(\d+)", _flags)
if _m is None or int(_m.group(1)) < 8:
    if _m is not None:
        _flags = _flags.replace(_m.group(0), "")
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, (
        "tests require 8 simulated devices; conftest must run before backend init"
    )
    return devs[:8]
