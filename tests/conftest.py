"""Test backbone: simulate an 8-device mesh on CPU.

Apex emulates multi-node topology by spawning one NCCL process per local GPU
(apex/transformer/testing/distributed_test_base.py (U)). On the XLA side we
do strictly better (SURVEY.md §4): force the host platform to expose 8
virtual CPU devices and run every distributed test single-process on a real
``jax.sharding.Mesh``. Must run before any jax backend is initialised.
"""

import os

import re

_flags = os.environ.get("XLA_FLAGS", "")
_m = re.search(r"--xla_force_host_platform_device_count=(\d+)", _flags)
if _m is None or int(_m.group(1)) < 8:
    if _m is not None:
        _flags = _flags.replace(_m.group(0), "")
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

# Persistent compile cache (the repo-local .jax_cache bench already
# uses): test models are tiny, so XLA compile time dominates the
# CPU-mesh suite — a warm cache halves wall time. Set via jax.config,
# NOT os.environ: the example-smoke subprocesses must not inherit it
# (this runtime crashes restoring a cached executable alongside a
# checkpoint resume — heap corruption in jaxlib, numpy-fallback
# confirmed native-runtime-clean). An existing JAX_COMPILATION_CACHE_DIR
# wins (empty value disables, matching _capabilities).
if "JAX_COMPILATION_CACHE_DIR" not in os.environ:
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, (
        "tests require 8 simulated devices; conftest must run before backend init"
    )
    return devs[:8]
