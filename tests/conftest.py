"""Test backbone: simulate an 8-device mesh on CPU.

Apex emulates multi-node topology by spawning one NCCL process per local GPU
(apex/transformer/testing/distributed_test_base.py (U)). On the XLA side we
do strictly better (SURVEY.md §4): force the host platform to expose 8
virtual CPU devices and run every distributed test single-process on a real
``jax.sharding.Mesh``. Must run before any jax backend is initialised.
"""

import os

import re

_flags = os.environ.get("XLA_FLAGS", "")
_m = re.search(r"--xla_force_host_platform_device_count=(\d+)", _flags)
if _m is None or int(_m.group(1)) < 8:
    if _m is not None:
        _flags = _flags.replace(_m.group(0), "")
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

# Persistent compile cache (the repo-local .jax_cache bench already
# uses): test models are tiny, so XLA compile time dominates the
# CPU-mesh suite — a warm cache halves wall time. Set via jax.config,
# NOT os.environ: the example-smoke subprocesses must not inherit it
# (this runtime crashes restoring a cached executable alongside a
# checkpoint resume — heap corruption in jaxlib, numpy-fallback
# confirmed native-runtime-clean). An existing JAX_COMPILATION_CACHE_DIR
# wins (empty value disables, matching _capabilities).
if "JAX_COMPILATION_CACHE_DIR" not in os.environ:
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, (
        "tests require 8 simulated devices; conftest must run before backend init"
    )
    return devs[:8]


# --- tier-1 marker audit -----------------------------------------------------
#
# The tier-1 run (-m 'not slow') has a hard wall-clock budget
# (ROADMAP.md). A test that quietly grows past ~60 s belongs behind the
# `slow` marker — this hook turns such a test's own PASSING report into
# a failure naming it, so the budget stays honest as suites grow
# instead of eroding one slow test at a time. Tunable/disable-able via
# APEX_TPU_TIER1_BUDGET_S (0 disables — e.g. profiling runs under a
# debugger, where wall time means nothing).
#
# The audit only arms on a WARM compile cache: per-test wall time
# includes XLA compiles, and a cold .jax_cache (fresh clone, wiped
# cache — the suite is ~25 min cold vs ~10 min warm) would spuriously
# fail compile-heavy tests that are well inside budget warm. An
# explicit APEX_TPU_TIER1_BUDGET_S overrides the heuristic either way.
#
# Static sibling: the TIER1-COST lint rule (apex_tpu.analysis) flags
# the known expensive *pattern* — a test calling Engine.warmup()
# without the slow marker — before the budget is ever spent; this hook
# stays as the backstop for everything the pattern can't see. The pair
# is kept honest by tests/test_static_analysis.py (lint battery over
# tests/, allowlist pinned) and test_marker_audit.py (this predicate).


def _compile_cache_warm(min_entries: int = 500) -> bool:
    d = jax.config.jax_compilation_cache_dir
    try:
        return d is not None and len(os.listdir(d)) >= min_entries
    except OSError:
        return False


TIER1_BUDGET_S = (
    float(os.environ["APEX_TPU_TIER1_BUDGET_S"])
    if "APEX_TPU_TIER1_BUDGET_S" in os.environ
    else (60.0 if _compile_cache_warm() else 0.0))


def audit_overtime(duration_s: float, has_slow_marker: bool,
                   budget_s: float = TIER1_BUDGET_S) -> bool:
    """THE audit predicate (unit-tested in test_marker_audit.py): an
    unmarked test over the budget is an offender; slow-marked tests are
    exempt at any duration, and a non-positive budget disables the
    audit."""
    return budget_s > 0 and duration_s > budget_s and not has_slow_marker


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    outcome = yield
    rep = outcome.get_result()
    if rep.when != "call" or not rep.passed:
        return  # only audit tests that would otherwise pass
    if audit_overtime(rep.duration,
                      item.get_closest_marker("slow") is not None):
        rep.outcome = "failed"
        rep.longrepr = (
            f"tier-1 marker audit: {item.nodeid} took "
            f"{rep.duration:.1f}s > {TIER1_BUDGET_S:.0f}s without "
            f"@pytest.mark.slow — mark it slow (it runs in the soak "
            f"tier) or make it faster; the tier-1 budget is a hard "
            f"timeout (ROADMAP.md). Set APEX_TPU_TIER1_BUDGET_S to "
            f"tune/disable.")
