"""Flash-decode kernel oracles (`kernels/decode_attention.py`).

Oracle pattern (SURVEY.md §4): the Pallas kernel vs the materialised-
scores XLA decode path with per-dtype tolerances — both standalone
(kernel vs fp32 numpy reference) and integrated (a full ``decode_step``
with ``decode_attn_impl="kernel"`` vs ``"xla"``), plus the one-column
cache-write contract: every cache byte outside the written column is
bit-identical to the input."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import mesh as mx
from apex_tpu.kernels import decode_attention
from apex_tpu.kernels.decode_attention import (
    decode_attention_quantized,
    quantize_kv_rows,
)
from apex_tpu.models import gpt
from apex_tpu.transformer.testing import standalone_gpt_config

_TOL = {
    jnp.float32: dict(rtol=2e-5, atol=2e-5),
    jnp.bfloat16: dict(rtol=3e-2, atol=3e-2),
    jnp.float16: dict(rtol=2e-3, atol=2e-3),
}


def _reference(q, k_new, v_new, k_cache, v_cache, pos):
    """fp32 numpy: write the column, mask ``<= pos``, plain softmax."""
    q, k_new, v_new, k_cache, v_cache = (
        np.asarray(t, np.float32)
        for t in (q, k_new, v_new, k_cache, v_cache))
    b, h, S, d = k_cache.shape
    kc, vc = k_cache.copy(), v_cache.copy()
    for i in range(b):
        kc[i, :, int(pos[i])] = k_new[i]
        vc[i, :, int(pos[i])] = v_new[i]
    s = np.einsum("bhd,bhsd->bhs", q, kc) / np.sqrt(d)
    valid = np.arange(S)[None, None] <= np.asarray(pos)[:, None, None]
    s = np.where(valid, s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhs,bhsd->bhd", p, vc), kc, vc


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16, jnp.float16])
def test_kernel_matches_fp32_reference(dtype):
    """Standalone oracle across dtypes, at a horizon that is not a
    multiple of the split-K chunk (exercises the padded tail) and with
    per-row positions spanning first/mid/last slots."""
    b, h, S, d = 3, 4, 19, 32
    ks = jax.random.split(jax.random.PRNGKey(0), 5)
    mk = lambda k, shp: (jax.random.normal(k, shp) * 0.5).astype(dtype)
    q = mk(ks[0], (b, h, d))
    k_new = mk(ks[1], (b, h, d))
    v_new = mk(ks[2], (b, h, d))
    k_cache = mk(ks[3], (b, h, S, d))
    v_cache = mk(ks[4], (b, h, S, d))
    pos = jnp.asarray([2, 0, 18], jnp.int32)
    out, kc, vc = jax.jit(decode_attention)(
        q, k_new, v_new, k_cache, v_cache, pos)
    ref_out, ref_kc, ref_vc = _reference(
        q, k_new, v_new, k_cache, v_cache, pos)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), ref_out, **_TOL[dtype])
    # one-column write contract: outside the written column the cache
    # is BIT-identical to the input; the column holds k_new/v_new
    for got, want, orig in ((kc, ref_kc, k_cache), (vc, ref_vc, v_cache)):
        got = np.asarray(got, np.float32)
        orig = np.asarray(orig, np.float32)
        col = np.zeros((b, h, S, d), bool)
        for i in range(b):
            col[i, :, int(pos[i])] = True
        np.testing.assert_array_equal(got[~col], orig[~col])
        np.testing.assert_allclose(got[col], want[col], **_TOL[dtype])


def test_kernel_masks_stale_cache_garbage():
    """Entries past a row's position must be exact softmax zeros: a
    cache whose masked tail holds huge garbage yields the same output
    as one holding zeros (the engine's padded-prefill contract)."""
    b, h, S, d = 2, 2, 12, 32
    ks = jax.random.split(jax.random.PRNGKey(1), 5)
    q = jax.random.normal(ks[0], (b, h, d))
    k_new = jax.random.normal(ks[1], (b, h, d))
    v_new = jax.random.normal(ks[2], (b, h, d))
    k_cache = jax.random.normal(ks[3], (b, h, S, d))
    v_cache = jax.random.normal(ks[4], (b, h, S, d))
    pos = jnp.asarray([3, 7], jnp.int32)
    tail = jnp.arange(S)[None, None, :, None] > pos[:, None, None, None]
    run = jax.jit(decode_attention)
    out_clean, _, _ = run(
        q, k_new, v_new,
        jnp.where(tail, 0.0, k_cache), jnp.where(tail, 0.0, v_cache), pos)
    out_junk, _, _ = run(
        q, k_new, v_new,
        jnp.where(tail, 1e30, k_cache), jnp.where(tail, -1e30, v_cache),
        pos)
    np.testing.assert_array_equal(
        np.asarray(out_clean), np.asarray(out_junk))


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_decode_step_kernel_matches_xla(devices8, dtype):
    """Integration oracle: a full ``decode_step`` (vector per-slot
    positions, tp sharded) through ``decode_attn_impl="kernel"``
    matches the materialised-scores XLA path at unchanged per-dtype
    tolerances — logits AND updated cache."""
    cfg0 = standalone_gpt_config(vocab_size=96, seq_len=32,
                                 compute_dtype=dtype)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (4, 6), 0, 96)
    tok = jax.random.randint(jax.random.PRNGKey(2), (4,), 0, 96)
    pos = jnp.asarray([6, 3, 1, 5], jnp.int32)
    outs = {}
    for tp in (1, 2):
        mesh = mx.build_mesh(tp=tp, devices=devices8[:tp])
        for impl in ("xla", "kernel"):
            cfg = dataclasses.replace(cfg0, decode_attn_impl=impl)
            params = gpt.init(cfg, jax.random.PRNGKey(0))
            pspecs = gpt.param_specs(cfg)

            def run(p, t, tk):
                cache, _ = gpt.prefill(cfg, p, t, max_len=cfg.seq_len)
                return gpt.decode_step(cfg, p, cache, tk, pos)

            lg, cache = jax.jit(jax.shard_map(
                run, mesh=mesh,
                in_specs=(pspecs, P(None, None), P(None)),
                out_specs=(P(None, None),
                           P(None, None, None, "tp", None, None)),
                check_vma=False))(params, prompt, tok)
            outs[(tp, impl)] = (np.asarray(lg, np.float32),
                                np.asarray(cache, np.float32))
    tol = _TOL[dtype]
    for tp in (1, 2):
        got_lg, got_c = outs[(tp, "kernel")]
        want_lg, want_c = outs[(tp, "xla")]
        np.testing.assert_allclose(got_lg, want_lg, err_msg=f"tp{tp}",
                                   **tol)
        np.testing.assert_allclose(got_c, want_c, err_msg=f"tp{tp}",
                                   **tol)


_QTOL = {"int8": dict(rtol=3e-2, atol=3e-2),
         "fp8": dict(rtol=6e-2, atol=6e-2)}


@pytest.mark.parametrize("kind", ["int8", "fp8"])
def test_quantized_kernel_matches_fp32_reference(kind):
    """Quantized-cache kernel oracle: output within the quantization
    error band of the unquantized fp32 reference, and the one-column
    write contract holds on BOTH planes — outside the written column
    the int8/fp8 data and fp32 scales are bit-identical to the input,
    the column holds exactly ``quantize_kv_rows(new)``."""
    b, h, S, d = 3, 4, 19, 32
    ks = jax.random.split(jax.random.PRNGKey(3), 5)
    mk = lambda k, shp: jax.random.normal(k, shp) * 0.5
    q = mk(ks[0], (b, h, d))
    k_new = mk(ks[1], (b, h, d))
    v_new = mk(ks[2], (b, h, d))
    k_raw = mk(ks[3], (b, h, S, d))
    v_raw = mk(ks[4], (b, h, S, d))
    kq0, ks0 = quantize_kv_rows(k_raw, kind)
    vq0, vs0 = quantize_kv_rows(v_raw, kind)
    pos = jnp.asarray([2, 0, 18], jnp.int32)
    out, kq, ksc, vq, vsc = jax.jit(
        lambda *a: decode_attention_quantized(
            *a, kind=kind))(q, k_new, v_new, kq0, ks0, vq0, vs0, pos)
    # reference: unquantized fp32 math over the DEQUANTIZED cache (the
    # cache held quantized values; the new column is exact pre-quant)
    deq = lambda qv, s: np.asarray(qv, np.float32) * np.asarray(
        s, np.float32)[..., None]
    ref_out, _, _ = _reference(q, k_new, v_new, deq(kq0, ks0),
                               deq(vq0, vs0), pos)
    np.testing.assert_allclose(np.asarray(out, np.float32), ref_out,
                               **_QTOL[kind])
    # write contract, both planes
    col = np.zeros((b, h, S), bool)
    for i in range(b):
        col[i, :, int(pos[i])] = True
    for got, orig, new in ((kq, kq0, k_new), (vq, vq0, v_new)):
        got = np.asarray(got, np.float32)
        orig = np.asarray(orig, np.float32)
        np.testing.assert_array_equal(got[~col], orig[~col])
        want_q, _ = quantize_kv_rows(new, kind)
        np.testing.assert_array_equal(
            got[col].reshape(b, h, d), np.asarray(want_q, np.float32))
    for got, orig, new in ((ksc, ks0, k_new), (vsc, vs0, v_new)):
        got, orig = np.asarray(got), np.asarray(orig)
        np.testing.assert_array_equal(got[~col], orig[~col])
        _, want_s = quantize_kv_rows(new, kind)
        np.testing.assert_array_equal(got[col].reshape(b, h),
                                      np.asarray(want_s))


def test_quantized_kernel_masks_stale_garbage():
    """Positions past a row's ``pos`` are exact softmax zeros even when
    the quantized tail holds saturated garbage and the scale plane
    holds NaN (an uninitialised-HBM bit pattern a fresh fp32 plane can
    legally contain)."""
    b, h, S, d = 2, 2, 12, 32
    ks = jax.random.split(jax.random.PRNGKey(5), 5)
    q = jax.random.normal(ks[0], (b, h, d))
    k_new = jax.random.normal(ks[1], (b, h, d))
    v_new = jax.random.normal(ks[2], (b, h, d))
    kq0, ks0 = quantize_kv_rows(
        jax.random.normal(ks[3], (b, h, S, d)), "int8")
    vq0, vs0 = quantize_kv_rows(
        jax.random.normal(ks[4], (b, h, S, d)), "int8")
    pos = jnp.asarray([3, 7], jnp.int32)
    tail3 = jnp.arange(S)[None, None, :] > pos[:, None, None]
    tail4 = tail3[..., None]
    run = jax.jit(lambda *a: decode_attention_quantized(
        *a, kind="int8"))
    out_clean, *_ = run(q, k_new, v_new,
                        jnp.where(tail4, 0, kq0),
                        jnp.where(tail3, 0.0, ks0),
                        jnp.where(tail4, 0, vq0),
                        jnp.where(tail3, 0.0, vs0), pos)
    out_junk, *_ = run(q, k_new, v_new,
                       jnp.where(tail4, jnp.int8(-127), kq0),
                       jnp.where(tail3, jnp.float32(jnp.nan), ks0),
                       jnp.where(tail4, jnp.int8(127), vq0),
                       jnp.where(tail3, jnp.float32(jnp.nan), vs0), pos)
    np.testing.assert_array_equal(np.asarray(out_clean),
                                  np.asarray(out_junk))


def test_decode_attention_validation():
    b, h, S, d = 2, 2, 8, 32
    z3 = jnp.zeros((b, h, d))
    z4 = jnp.zeros((b, h, S, d))
    with pytest.raises(ValueError, match="expected q"):
        decode_attention(z4, z3, z3, z4, z4, jnp.zeros((b,), jnp.int32))
    with pytest.raises(ValueError, match="pos must be"):
        decode_attention(z3, z3, z3, z4, z4, jnp.zeros((3,), jnp.int32))
    with pytest.raises(ValueError, match="unknown decode_attn_impl"):
        gpt._decode_attn_impl(
            standalone_gpt_config(decode_attn_impl="nope"), 8)
    # off-TPU "auto" resolves to the XLA path (Pallas runs interpreted),
    # and f16 does everywhere (the kernel boundary would widen the full
    # caches per layer per token)
    assert gpt._decode_attn_impl(standalone_gpt_config(), 4096) == "xla"
    assert gpt._decode_attn_impl(
        standalone_gpt_config(compute_dtype=jnp.float16), 4096) == "xla"
