"""apex_tpu.serving.journal — durable WAL + crash-safe warm restart.

Layers, cheapest first: the shared atomic-write helper's crash-cut
contract, stdlib framing units (CRC scan, torn tails, segment
rotation, compaction — no engine, no jax arrays), then the tier-1
recovery oracle: run a journaled scheduler partway, "crash" at the
fsync boundary (seal the journal, drop the device state), recover
with :func:`recover_scheduler`, and every stream — greedy AND
seeded-sampled — finishes bit-identical to a run that was never
interrupted, with zero recompiles. Long-suite: the LoRA-adapter and
paged/int8 compositions recover onto FRESH engines (registrations
replayed from seeds), and the real thing — a subprocess SIGKILL
drill through :func:`apex_tpu.serving.resilience.sigkill_drill`.
"""

import os

import jax
import pytest

from apex_tpu import _atomic
from apex_tpu import mesh as mx
from apex_tpu.models import gpt
from apex_tpu.serving import Request, SamplingParams
from apex_tpu.serving.engine import Engine, EngineConfig
from apex_tpu.serving.journal import (
    Journal,
    JournalError,
    recover_scheduler,
    replay_into,
    replay_state,
    scan_journal,
)
from apex_tpu.serving.scheduler import Scheduler
from apex_tpu.transformer.testing import standalone_gpt_config

VOCAB = 96


def _cfg(**overrides):
    base = dict(vocab_size=VOCAB, seq_len=64)
    base.update(overrides)
    return standalone_gpt_config(**base)


@pytest.fixture(scope="module")
def model(devices8):
    cfg = _cfg()
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    return cfg, params, mesh


def _reqs(n, *, seed0=7400, max_tokens=6, adapter=None):
    """Mixed greedy + seeded-sampled trace (deterministic per request
    — the property that makes journal replay bit-identical)."""
    out = []
    for i in range(n):
        p_len = 2 + (3 * i) % 6
        prompt = [int(t) for t in jax.random.randint(
            jax.random.PRNGKey(seed0 + i), (p_len,), 0, VOCAB)]
        sp = (SamplingParams(temperature=0.9, top_k=7, seed=seed0 + i)
              if i % 2 else SamplingParams())
        kw = {} if adapter is None else {"adapter": adapter(i)}
        out.append(Request(f"j{seed0}_{i}", prompt,
                           max_tokens=max_tokens, sampling=sp, **kw))
    return out


def _drain(sched):
    sched.run_until_idle()
    return {rid: c.tokens for rid, c in sched.completions.items()}


# --- the shared atomic-write helper (apex_tpu._atomic) ----------------------


def test_atomic_write_crash_cut_leaves_nothing(tmp_path):
    """A writer that dies mid-write must leave neither a truncated
    destination nor temp litter — the contract every checkpoint /
    bundle / native-build / journal-compaction site now shares."""
    dst = str(tmp_path / "artifact.bin")

    def boom(f):
        f.write(b"half a paylo")
        raise RuntimeError("power cut")

    with pytest.raises(RuntimeError, match="power cut"):
        _atomic.atomic_write(dst, boom)
    assert not os.path.exists(dst)
    assert os.listdir(str(tmp_path)) == []

    _atomic.atomic_write(dst, lambda f: f.write(b"whole payload"))
    with open(dst, "rb") as f:
        assert f.read() == b"whole payload"
    # overwrite is also all-or-nothing: a failed rewrite keeps the old
    with pytest.raises(RuntimeError):
        _atomic.atomic_write(dst, boom)
    with open(dst, "rb") as f:
        assert f.read() == b"whole payload"
    assert os.listdir(str(tmp_path)) == ["artifact.bin"]


# --- framing + scan (stdlib, no engine) -------------------------------------


def test_append_scan_roundtrip_and_stats(tmp_path):
    jd = str(tmp_path / "wal")
    with Journal(jd, fsync="always") as j:
        assert j.append("submit", request_id="r0", prompt=[1, 2]) == 1
        assert j.append("extend", request_id="r0", start=0,
                        tokens=[5, 6], logprobs=[0.0, -1.5]) == 2
        assert j.append("finish", request_id="r0", reason="length") == 3
        assert j.seq == 3 and j.appends == 3
        assert j.fsyncs >= 3          # policy always: one per append
        st = j.stats()
        assert st["appends"] == 3.0 and st["segments"] == 1.0
        assert st["truncated_bytes"] == 0.0
    records, truncated = scan_journal(jd)
    assert truncated == 0
    assert [r["kind"] for r in records] == ["submit", "extend", "finish"]
    assert [r["seq"] for r in records] == [1, 2, 3]
    assert records[1]["tokens"] == [5, 6]
    # reopen resumes the sequence from the scanned tail
    with Journal(jd) as j2:
        assert j2.seq == 3
        assert j2.append("submit", request_id="r1") == 4


def test_constructor_validation(tmp_path):
    with pytest.raises(ValueError, match="fsync policy"):
        Journal(str(tmp_path / "a"), fsync="sometimes")
    with pytest.raises(ValueError, match="segment_bytes"):
        Journal(str(tmp_path / "b"), segment_bytes=16)
    with pytest.raises(JournalError, match="no journal directory"):
        scan_journal(str(tmp_path / "missing"))


def test_torn_tail_truncates_at_first_bad_crc(tmp_path):
    jd = str(tmp_path / "wal")
    with Journal(jd, fsync="always") as j:
        for i in range(4):
            j.append("submit", request_id=f"r{i}")
        seg = os.path.join(jd, j.segments()[-1])

    # a torn FRAME (half a header) hides only itself
    good_size = os.path.getsize(seg)
    with open(seg, "ab") as f:
        f.write(b"\x07\x00")
    records, truncated = scan_journal(jd)
    assert len(records) == 4 and truncated == 2

    # a bad CRC mid-file hides everything AFTER it too: a record that
    # survives a flipped predecessor could replay state the lost
    # records invalidated
    with open(seg, "r+b") as f:
        f.seek(good_size // 2)
        byte = f.read(1)
        f.seek(good_size // 2)
        f.write(bytes([byte[0] ^ 0xFF]))
    records, truncated = scan_journal(jd)
    assert len(records) < 4
    assert truncated > 2

    # repair physically truncates; append then continues cleanly
    n_before = len(records)
    scan_journal(jd, repair=True)
    assert os.path.getsize(seg) < good_size
    with Journal(jd, fsync="always") as j2:
        j2.append("submit", request_id="post_repair")
    records, truncated = scan_journal(jd)
    assert truncated == 0
    assert [r["request_id"] for r in records] == \
        [f"r{i}" for i in range(n_before)] + ["post_repair"]


def test_tear_drops_later_segments(tmp_path):
    """A tear in segment k makes every LATER segment suspect: its
    records may extend state the lost tail invalidated, so scan stops
    at the tear and repair removes the later segments entirely."""
    jd = str(tmp_path / "wal")
    with Journal(jd, fsync="always", segment_bytes=4096) as j:
        blob = list(range(200))
        while j.rotations < 2:
            j.append("extend", request_id="r0", start=0, tokens=blob,
                     logprobs=[])
        segs = [os.path.join(jd, s) for s in j.segments()]
        total = j.appends
    assert len(segs) >= 3
    with open(segs[0], "r+b") as f:
        f.truncate(os.path.getsize(segs[0]) - 3)
    records, truncated = scan_journal(jd)
    assert len(records) < total
    assert truncated >= sum(os.path.getsize(s) for s in segs[1:])
    scan_journal(jd, repair=True)
    assert [os.path.exists(s) for s in segs] == [True, False, False]
    with Journal(jd) as j2:     # reopens the repaired tail for append
        j2.append("submit", request_id="r1")
    _, truncated = scan_journal(jd)
    assert truncated == 0


def test_rotation_keeps_order_and_manifest(tmp_path):
    jd = str(tmp_path / "wal")
    with Journal(jd, segment_bytes=4096) as j:
        payload = list(range(300))
        while j.rotations < 2:
            j.append("extend", request_id="r0", start=0,
                     tokens=payload, logprobs=[])
        assert len(j.segments()) == j.rotations + 1
        assert j.last_sealed is not None
        name, n_records, n_bytes = j.last_sealed
        assert n_records > 0 and n_bytes <= 4096 + 8 + len(
            str(payload)) * 2
        assert os.path.exists(os.path.join(jd, "journal.json"))
    records, truncated = scan_journal(jd)
    assert truncated == 0
    assert [r["seq"] for r in records] == \
        list(range(1, len(records) + 1))


def test_compaction_drops_finished_keeps_live(tmp_path):
    jd = str(tmp_path / "wal")
    j = Journal(jd, segment_bytes=4096)
    j.append("meta", format=1, engine_spec={"model": {"x": 1}})
    j.append("adapter", name="lora_a", seed=7, rank=4, adapter_id=1)
    j.append("prefix", tokens=[1, 2, 3, 4])
    for i in range(3):
        j.append("submit", request_id=f"r{i}", order=i,
                 prompt=[i], max_tokens=6, temperature=0.0)
    j.append("extend", request_id="r0", start=0, tokens=[10, 11],
             logprobs=[0.0, 0.0])
    j.append("extend", request_id="r1", start=0, tokens=[20],
             logprobs=[0.0])
    j.append("extend", request_id="r1", start=1, tokens=[21],
             logprobs=[0.0])
    j.append("finish", request_id="r0", reason="length")
    j.append("park", request_id="r2")
    res = j.compact()
    assert res["dropped_finished"] == 1
    assert len(j.segments()) == 1

    records, truncated = scan_journal(jd)
    assert truncated == 0
    st = replay_state(records)
    assert st.meta["engine_spec"] == {"model": {"x": 1}}
    assert [a["name"] for a in st.adapters] == ["lora_a"]
    assert st.prefixes == [[1, 2, 3, 4]]
    assert set(st.requests) == {"r1", "r2"}      # r0 finished: gone
    assert st.requests["r1"]["emitted"] == [20, 21]
    assert st.requests["r2"]["parked"] is True
    assert [r["request_id"] for r in st.unfinished()] == ["r1", "r2"]

    # crash-safety of compaction itself: absolute extend offsets make
    # replay idempotent over a duplicated suffix (old segment replayed
    # AFTER the compacted rewrite, as a crash between the new-segment
    # write and the old-segment unlink would)
    dup = replay_state(records + records)
    assert dup.requests["r1"]["emitted"] == [20, 21]
    assert dup.anomalies == 0

    # appending continues on the compacted tail
    j.append("extend", request_id="r1", start=2, tokens=[22],
             logprobs=[0.0])
    j.close()
    st2 = replay_state(scan_journal(jd)[0])
    assert st2.requests["r1"]["emitted"] == [20, 21, 22]


def test_auto_compaction_threshold(tmp_path):
    jd = str(tmp_path / "wal")
    with Journal(jd, compact_min_finished=2) as j:
        for i in range(2):
            j.append("submit", request_id=f"r{i}", order=i, prompt=[i],
                     max_tokens=4)
            j.append("finish", request_id=f"r{i}", reason="length")
        assert j.maybe_compact() is True
        assert j.compactions == 1
        assert j.maybe_compact() is False    # counter reset on compact
    assert replay_state(scan_journal(jd)[0]).requests == {}


def test_auto_compaction_failure_degrades_not_closes(tmp_path,
                                                     monkeypatch):
    """ENOSPC strikes exactly when compaction runs (it writes a whole
    new segment). A failed rewrite must leave the journal open for
    appends on its previous tail — not permanently 'closed' so every
    later scheduler append raises JournalError — and maybe_compact
    must degrade the failure to a counted stat instead of raising
    into the fetch boundary."""
    jd = str(tmp_path / "wal")
    j = Journal(jd, compact_min_finished=1)
    j.append("submit", request_id="r0", order=0, prompt=[1],
             max_tokens=4)
    j.append("finish", request_id="r0", reason="length")
    j.append("submit", request_id="r1", order=1, prompt=[2],
             max_tokens=4)

    def no_space(path, write_fn, **kw):
        raise OSError(28, "No space left on device")

    monkeypatch.setattr(_atomic, "atomic_write", no_space)
    assert j.maybe_compact() is False
    assert j.compaction_errors == 1
    # manual compact() re-raises, but still restores the tail first
    j.append("finish", request_id="r0", reason="length")
    with pytest.raises(OSError, match="No space"):
        j.compact()
    # the journal still journals: append, then recover the disk and
    # compact for real
    j.append("extend", request_id="r1", start=0, tokens=[7],
             logprobs=[0.0])
    monkeypatch.undo()
    res = j.compact()
    assert res["dropped_finished"] == 1
    assert j.compactions == 1
    j.append("finish", request_id="r1", reason="length")
    j.close()
    records, truncated = scan_journal(jd)
    assert truncated == 0
    st = replay_state(records)
    assert set(st.requests) == {"r1"}
    assert st.requests["r1"]["emitted"] == [7]
    assert st.requests["r1"]["finished"] is True


class _StubScheduler:
    """The replay_into surface without an engine: hands out SEQUENTIAL
    adapter ids (the real engine's allocation policy — the property
    the id remap exists for) and records every resubmission."""

    recorder = None
    telemetry = None

    def __init__(self):
        self._journal_recovered = 0
        self._next_adapter = 1
        self.registered = []
        self.submitted = []

    def clock(self):
        return 100.0

    def register_adapter(self, weights=None, *, name=None, seed=None):
        aid = self._next_adapter
        self._next_adapter += 1
        self.registered.append((name, seed, aid))
        return aid

    def register_prefix(self, tokens):
        return 1

    def submit(self, req, *, replay_prefix=None, replay_logprobs=None):
        self.submitted.append(req)


def test_replay_remaps_adapter_ids_across_skipped_registrations():
    """Engine adapter ids are sequential and recovery skips
    seed-null (explicit-weights) registrations, so every adapter
    registered AFTER a skipped one lands on a SHIFTED id on the fresh
    engine. Resubmitting with the journaled id would silently run the
    request under the wrong adapter weights: replay must remap each
    request's id through what register_adapter actually returned, and
    skip (counted) any request whose id has no mapping."""
    records = [
        {"kind": "adapter", "name": "explicit", "seed": None,
         "rank": 4, "adapter_id": 1},
        {"kind": "adapter", "name": "seeded", "seed": 5,
         "rank": 4, "adapter_id": 2},
        {"kind": "submit", "request_id": "base", "order": 0,
         "prompt": [1], "max_tokens": 4, "adapter": 0},
        {"kind": "submit", "request_id": "shifted", "order": 1,
         "prompt": [2], "max_tokens": 4, "adapter": 2},
        {"kind": "submit", "request_id": "dead", "order": 2,
         "prompt": [3], "max_tokens": 4, "adapter": 1},
        {"kind": "submit", "request_id": "lost", "order": 3,
         "prompt": [4], "max_tokens": 4, "adapter": 9},  # torn away
    ]
    sched = _StubScheduler()
    report = replay_into(sched, records)
    # only the seeded adapter re-registers — and the fresh engine
    # hands it id 1, not its journaled id 2
    assert [(n, s) for n, s, _ in sched.registered] == [("seeded", 5)]
    assert {r.request_id: r.adapter for r in sched.submitted} == \
        {"base": 0, "shifted": 1}
    assert report.requests == 2
    assert report.adapters == 1
    assert report.skipped_adapters == 1          # 'explicit'
    assert report.skipped_adapter_requests == 2  # 'dead' + 'lost'


def test_replay_maps_adapters_by_name_across_double_recovery():
    """A recovered scheduler journals its own re-registrations and
    resubmissions into the SAME journal, so after a second crash the
    log holds two generations of adapter ids — and the fresh
    generation can even REUSE a dead explicit-weights registration's
    old id. Submit records carry adapter_name precisely so replay
    maps by the stable name and never crosses id generations."""
    records = [
        {"kind": "adapter", "name": "explicit", "seed": None,
         "rank": 4, "adapter_id": 1},
        {"kind": "adapter", "name": "adapter-seed-9", "seed": 9,
         "rank": 4, "adapter_id": 2},
        {"kind": "submit", "request_id": "pinned", "order": 0,
         "prompt": [1], "max_tokens": 4, "adapter": 1,
         "adapter_name": "explicit"},
        {"kind": "submit", "request_id": "live", "order": 1,
         "prompt": [2], "max_tokens": 4, "adapter": 2,
         "adapter_name": "adapter-seed-9"},
        # what recovery #1 appended: the seeded adapter re-registered
        # at id 1 (the dead registration's old id!) and 'live'
        # resubmitted under it
        {"kind": "adapter", "name": "adapter-seed-9", "seed": 9,
         "rank": 4, "adapter_id": 1},
        {"kind": "submit", "request_id": "live", "order": 1,
         "prompt": [2], "max_tokens": 4, "adapter": 1,
         "adapter_name": "adapter-seed-9"},
    ]
    sched = _StubScheduler()
    report = replay_into(sched, records)
    # one registration per NAME, replayed once from its seed
    assert [(n, s) for n, s, _ in sched.registered] == \
        [("adapter-seed-9", 9)]
    assert {r.request_id: r.adapter for r in sched.submitted} == \
        {"live": 1}
    # 'pinned' names the dead explicit adapter: skipped, even though
    # its journaled id (1) is now occupied by the seeded adapter
    assert report.skipped_adapters == 1
    assert report.skipped_adapter_requests == 1
    assert report.requests == 1


def test_replay_state_counts_gap_anomalies(tmp_path):
    st = replay_state([
        {"kind": "submit", "request_id": "r0", "order": 0,
         "prompt": [1], "max_tokens": 4},
        {"kind": "extend", "request_id": "r0", "start": 3,
         "tokens": [9], "logprobs": [0.0]},       # gap: nothing at 0-2
        {"kind": "extend", "request_id": "ghost", "start": 0,
         "tokens": [1], "logprobs": [0.0]},       # never submitted
    ])
    assert st.anomalies == 2
    assert st.requests["r0"]["emitted"] == []


# --- the tier-1 recovery oracle ---------------------------------------------


def test_crash_recovery_streams_bit_identical(model, tmp_path):
    """THE durability oracle: journaled serving crashed at the fsync
    boundary recovers every unfinished stream and finishes it
    bit-identical to an uninterrupted run — greedy and seeded-sampled
    lanes alike — with zero recompiles (recovery admits through the
    same warmed programs) and the journal surface in summary()."""
    cfg, params, mesh = model
    jd = str(tmp_path / "wal")
    eng = Engine(cfg, params, mesh, EngineConfig(
        slots=2, max_prompt_len=8, max_seq_len=24,
        decode_chunk=2)).warmup()  # apex: noqa[TIER1-COST]: one warmed tiny engine drives reference, victim, and recovery (displaced: the pool-reset contract test went long-suite)
    try:
        reqs = _reqs(4)
        ref_sched = Scheduler(eng)
        for r in reqs:
            ref_sched.submit(r)
        ref = _drain(ref_sched)
        sen0 = eng.recompile_sentinel()

        eng.rebuild_slots()
        j = Journal(jd, fsync="batch")
        victim = Scheduler(eng, journal=j)
        for r in reqs:
            victim.submit(r)
        for _ in range(4):
            victim.step()
        prior = {rid: c.tokens for rid, c in
                 victim.completions.items()}
        assert 0 < len(prior) < len(reqs), (
            "crash point degenerate — tune step count so some "
            "requests are finished and some mid-flight")
        # the crash: seal at the fsync boundary (the durable point a
        # batch-policy journal guarantees), drop all device state
        j.close()
        eng.rebuild_slots()

        sched2, report = recover_scheduler(jd, lambda: eng)
        assert report.requests == len(reqs) - len(prior)
        assert report.truncated_bytes == 0
        recovered = _drain(sched2)
        sched2.journal.close()

        merged = dict(prior)
        merged.update(recovered)
        assert merged == ref, (
            f"recovered streams drifted: {merged} != {ref}")
        assert eng.recompile_sentinel() == sen0, \
            "recovery recompiled — replay missed a warmed variant"
        s = sched2.summary()
        assert s["journal_recovered_requests"] == float(report.requests)
        for key in ("journal_appends", "journal_bytes",
                    "journal_fsyncs", "journal_segments"):
            assert key in s
    finally:
        eng.close()


# --- long-suite compositions (fresh-engine recovery, SIGKILL) ---------------


@pytest.mark.slow  # fresh-engine + adapter warmups; tier-1 carries the single-engine oracle above
def test_recovery_replays_lora_adapters_onto_fresh_engine(model,
                                                          tmp_path):
    """Recovery after TOTAL loss: the replacement engine starts with
    an empty adapter pool, and replay re-registers the journaled
    seeded adapter before resubmitting its requests — adapter streams
    finish bit-identical to the uninterrupted run. The pool mixes an
    explicit-weights adapter (id 1, unreplayable) in FRONT of the
    seeded one (id 2), so recovery must remap the seeded requests
    onto the id the fresh engine assigns (1) — resubmitting the
    journaled id would decode under the wrong row."""
    cfg, params, mesh = model
    jd = str(tmp_path / "wal")
    ecfg = EngineConfig(slots=2, max_prompt_len=8, max_seq_len=24,
                        decode_chunk=2, adapter_slots=3)

    def build():
        return Engine(cfg, params, mesh, ecfg)

    explicit = gpt.init_lora_weights(cfg, ecfg.adapter_rank, 777)
    # even requests ride base weights, odd ones the SEEDED adapter
    # (journaled id 2 — shifted to 1 on the recovered engine)
    reqs = _reqs(4, seed0=8100, adapter=lambda i: 2 * (i % 2))
    with build().warmup() as eng:
        ref_sched = Scheduler(eng)
        assert ref_sched.register_adapter(
            explicit, name="explicit") == 1
        assert ref_sched.register_adapter(seed=123) == 2
        for r in reqs:
            ref_sched.submit(r)
        ref = _drain(ref_sched)

    with build().warmup() as eng2:
        j = Journal(jd)
        victim = Scheduler(eng2, journal=j)
        victim.register_adapter(explicit, name="explicit")
        victim.register_adapter(seed=123)
        for r in reqs:
            victim.submit(r)
        for _ in range(3):
            victim.step()
        prior = {rid: c.tokens for rid, c in
                 victim.completions.items()}
        j.close()

    sched2, report = recover_scheduler(jd, lambda: build())
    try:
        assert report.adapters == 1            # the seeded one
        assert report.skipped_adapters == 1    # 'explicit': seed=null
        assert report.skipped_adapter_requests == 0
        assert sched2.engine.adapters_registered == 1
        merged = dict(prior)
        merged.update(_drain(sched2))
        assert merged == ref, "adapter recovery drifted"
    finally:
        sched2.journal.close()
        sched2.engine.close()


@pytest.mark.slow  # int8+paged engine warmups; tier-1 carries the plain-cache oracle
def test_recovery_paged_int8_composition(devices8, tmp_path):
    """The composed cache modes ride the same journal: paged KV +
    int8 storage, crashed and recovered onto a fresh engine, emits
    the uninterrupted streams."""
    cfg = _cfg(kv_cache_dtype="int8")
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    jd = str(tmp_path / "wal")
    ecfg = EngineConfig(slots=2, max_prompt_len=8, max_seq_len=24,
                        decode_chunk=2, page_size=8)

    def build():
        return Engine(cfg, params, mesh, ecfg)

    reqs = _reqs(4, seed0=8200)
    with build().warmup() as eng:
        ref_sched = Scheduler(eng)
        for r in reqs:
            ref_sched.submit(r)
        ref = _drain(ref_sched)

    with build().warmup() as eng2:
        j = Journal(jd)
        victim = Scheduler(eng2, journal=j)
        for r in reqs:
            victim.submit(r)
        for _ in range(3):
            victim.step()
        prior = {rid: c.tokens for rid, c in
                 victim.completions.items()}
        j.close()

    sched2, report = recover_scheduler(jd, lambda: build())
    try:
        merged = dict(prior)
        merged.update(_drain(sched2))
        assert merged == ref, "paged/int8 recovery drifted"
        assert report.truncated_bytes == 0
    finally:
        sched2.journal.close()
        sched2.engine.close()


@pytest.mark.slow  # subprocess cold compiles (the persistent cache is deliberately disabled for children — see conftest)
def test_sigkill_drill_recovers_bit_identical(tmp_path):
    """The real crash: a child process is SIGKILLed mid-decode (no
    atexit, no flush — exactly what fsync discipline exists for) and
    a recovery process finishes every stream bit-identical to an
    uninterrupted reference child."""
    from apex_tpu.serving.resilience import sigkill_drill

    res = sigkill_drill(str(tmp_path), requests=3, max_tokens=10,
                        kill_after_tokens=6)
    assert res["parity"], (
        f"SIGKILL drill drifted: {res['reference']} != "
        f"{res['recovered']}")
    assert res["killed_at_tokens"] >= 6
    assert res["recovered_requests"] >= 1
    assert res["recovery_ms"] > 0.0
