"""apex_tpu.serving.tuner — the self-tuning serving control plane.

Headline oracles: (1) fake-clock convergence — with an injected
latency model making one operating point strictly dominant, the
controller finds it within a bounded number of probe windows, and
re-converges after the model shifts mid-run; (2) stream parity — an
autotuned run emits bit-identical per-request streams to a fixed-config
run of the same trace, including under a seeded FaultPlan (the
chunk-parity / pipelined==serial oracles extended across
controller-driven switching); (3) replayability — a post-mortem bundle
from an autotuned chaos run reproduces the controller's decision
sequence bit-identically from the recorded clocks
(``telemetry.replay.replay_tuner``); (4) pre-warm safety — the
controller never dispatches a variant warmup did not compile (ladder
validation at construction, per-variant cache sizes flat, and — slow
tier — an armed recompile guard across forced switching)."""

import dataclasses

import jax
import pytest

from apex_tpu import mesh as mx
from apex_tpu.models import gpt
from apex_tpu.serving import Request, SamplingParams
from apex_tpu.serving.engine import Engine, EngineConfig
from apex_tpu.serving.resilience import (
    FaultPlan,
    FaultSpec,
    ResilienceConfig,
)
from apex_tpu.serving.scheduler import Scheduler, SpecGateConfig
from apex_tpu.serving.tuner import (
    TUNER_FROZEN,
    TUNER_PROBING,
    TUNER_STEADY,
    Controller,
    TunerConfig,
    compare_decisions,
    parse_point,
    point_key,
)
from apex_tpu.telemetry.flightrec import FlightRecorder
from apex_tpu.transformer.testing import standalone_gpt_config

VOCAB = 96


# -- pure-controller harness (no jax work; the fake-clock unit half) ---------


def _fast_cfg(**kw):
    base = dict(decode_chunk=(1, 2, 4), pipeline_depth=(1, 2),
                probe_every=2, probe_chunks=1, min_measure_chunks=2)
    base.update(kw)
    return TunerConfig(**base)


_BASE = {"decode_chunk": 1, "pipeline_depth": 1, "max_admit_batch": 0,
         "spec_k": 0}


def _drive(ctl, quality, chunks):
    """Feed ``chunks`` observations where each point's
    tokens-per-second sample is exactly ``quality(point)`` (tokens=1,
    depth=1, wall=1/q — observe computes tokens*depth/wall = q):
    total, deterministic control of the injected latency model."""
    for _ in range(chunks):
        point = ctl.want_dispatch(0)
        ctl.observe(point, 1, 1.0 / quality(point), 1)


def test_controller_converges_to_dominant_point():
    """The acceptance oracle's unit half: a latency model with one
    strictly dominant operating point is found within a bounded
    number of probe windows — and when the model SHIFTS mid-run, the
    symmetric re-probe cadence re-converges onto the new optimum."""
    best = {"decode_chunk": 4, "pipeline_depth": 2}

    def quality(point):
        q = 1.0
        q *= {1: 1.0, 2: 2.0, 4: 4.0}[point["decode_chunk"]]
        q *= {1: 1.0, 2: 1.5}[point["pipeline_depth"]]
        return q

    ctl = Controller(_fast_cfg(), _BASE)

    def drive_until(q, target, max_chunks):
        for _ in range(max_chunks):
            if ctl.incumbent == target:
                return
            point = ctl.want_dispatch(0)
            ctl.observe(point, 1, 1.0 / q(point), 1)
        raise AssertionError(
            f"no convergence to {target} in {max_chunks} chunks — "
            f"stuck at {ctl.incumbent}")

    drive_until(quality, best, 200)
    # the BOUND: with a strictly dominant point, every winning probe
    # switches on its first window — at most one coordinate-descent
    # pass over the 3 non-incumbent candidates plus the walk's
    # intermediate wins (chunk 1→2→4, depth 1→2, one losing re-probe
    # in between). 8 windows is generous; unbounded search would blow
    # straight past it.
    assert ctl.probes_total <= 8, ctl.probes_total
    assert ctl.state() in (TUNER_STEADY, TUNER_PROBING)
    # the shift: small chunks at depth 1 now dominate (a burst of
    # short-budget traffic where wide chunks burn pad columns)
    flipped = {"decode_chunk": 1, "pipeline_depth": 1}

    def quality2(point):
        return 1.0 / quality(point)

    probes_before = ctl.probes_total
    drive_until(quality2, flipped, 300)
    assert ctl.probes_total - probes_before <= 10


def test_one_knob_per_window_and_probe_serialization():
    """Coordinate descent: every probe point differs from the
    incumbent in exactly ONE knob, and while a (non-depth) probe chunk
    is in flight the controller holds further dispatches."""
    ctl = Controller(_fast_cfg(), _BASE)
    seen_probe_points = []
    for _ in range(40):
        point = ctl.want_dispatch(0)
        if ctl.probe is not None:
            seen_probe_points.append(dict(point))
            if ctl.probe[0] != "pipeline_depth":
                # serialization: a second dispatch with one in flight
                # is held...
                assert ctl.want_dispatch(1) is None
            else:
                # ...except for the depth knob, whose candidate IS the
                # in-flight depth being measured
                assert ctl.want_dispatch(1) == point
        ctl.observe(point, 1, 1.0, 1)
    assert seen_probe_points, "no probe ever opened"
    for p in seen_probe_points:
        moved = [k for k in ctl.knobs if p[k] != ctl.base[k]]
        # vs the base incumbent (quality is flat — nothing switches)
        assert len(moved) == 1, p
    assert sum(ctl.switch_counts.values()) == 0  # flat model: no wins


def test_margin_hysteresis_holds_incumbent():
    """A challenger within the margin never displaces the incumbent —
    the noisy-tie flap the spec gate's hysteresis existed to kill."""
    ctl = Controller(_fast_cfg(decode_chunk=(1, 2), pipeline_depth=None,
                               margin=1.10), _BASE)

    def quality(point):  # chunk 2 is 5% better: inside the margin
        return 1.05 if point["decode_chunk"] == 2 else 1.0

    _drive(ctl, quality, 60)
    assert ctl.incumbent["decode_chunk"] == 1
    assert ctl.probes_total > 3  # it kept re-probing, kept reverting
    assert sum(ctl.switch_counts.values()) == 0


def test_freeze_aborts_probe_reverts_to_base_and_ignores_samples():
    """The hard-freeze contract: an active probe aborts (no decision
    from partial data), dispatches revert to the BASE point,
    observations are ignored, and thaw resumes cleanly."""
    rec = FlightRecorder(clock=lambda: 0.0)
    ctl = Controller(_fast_cfg(), _BASE, recorder=rec)

    def quality(point):
        return 2.0 if point["decode_chunk"] == 2 else 1.0

    # measure, then drive until a probe window opens
    for _ in range(200):
        point = ctl.want_dispatch(0)
        if ctl.probe is not None:
            break
        ctl.observe(point, 1, 1.0 / quality(point), 1)
    assert ctl.probe is not None
    ewma_before = ctl.incumbent_ewma
    ctl.freeze("constrained")
    assert ctl.probe is None and ctl.state() == TUNER_FROZEN
    assert ctl.want_dispatch(0) == {"decode_chunk": 1,
                                    "pipeline_depth": 1}
    ctl.observe({"decode_chunk": 1, "pipeline_depth": 1}, 1, 0.001, 1)
    assert ctl.incumbent_ewma == ewma_before  # frozen samples ignored
    ctl.freeze("replay")  # cause change records a fresh enter
    ctl.thaw()
    assert ctl.state() in (TUNER_STEADY, TUNER_PROBING)
    names = [e[2] for e in rec.events()]
    assert names.count("tuner_freeze") == 3  # enter, enter, exit
    aborts = [e for e in rec.events()
              if e[2] == "tuner_probe" and e[3][2] == "abort"]
    assert len(aborts) == 1


def test_decision_replay_bit_identical_from_recorded_inputs():
    """replay_decisions over the recorded tuner_obs/tuner_freeze
    inputs regenerates the probe/switch/freeze sequence EXACTLY —
    EWMA fields included (pure float arithmetic on recorded clocks)."""
    rec = FlightRecorder(clock=lambda: 0.0)
    ctl = Controller(_fast_cfg(), _BASE, recorder=rec)

    def quality(point):
        return (1.0 + 0.9 * (point["decode_chunk"] == 4)
                + 0.4 * (point["pipeline_depth"] == 2))

    _drive(ctl, quality, 25)
    ctl.freeze("rebuild")
    ctl.thaw()
    _drive(ctl, quality, 25)
    events = rec.to_dicts(rec.events())
    out = compare_decisions(_fast_cfg(), _BASE, events)
    assert out["mismatches"] == [], out["mismatches"]
    assert out["decisions_recorded"] == out["decisions_replayed"] > 0


def test_point_key_roundtrip_and_config_validation():
    p = {"decode_chunk": 8, "pipeline_depth": 2, "spec_k": 0}
    assert parse_point(point_key(p)) == p
    with pytest.raises(ValueError, match="no knob ladder"):
        Controller(TunerConfig(), _BASE)
    with pytest.raises(ValueError, match="margin"):
        Controller(TunerConfig(decode_chunk=(1, 2), margin=0.9), _BASE)
    with pytest.raises(ValueError, match="strictly increasing"):
        Controller(TunerConfig(decode_chunk=(2, 1)), _BASE)
    with pytest.raises(ValueError, match="base"):
        Controller(TunerConfig(decode_chunk=(2, 4)), _BASE)
    with pytest.raises(ValueError, match="probe_every"):
        Controller(TunerConfig(decode_chunk=(1, 2), probe_every=0),
                   _BASE)
    # every-ladder-a-singleton is a silently inert controller — reject
    # loudly (bench reads probes=0 as a broken A/B, operators would
    # read it as autotuning that is not happening)
    with pytest.raises(ValueError, match="single candidate"):
        Controller(TunerConfig(decode_chunk=(1,),
                               pipeline_depth=(1,)), _BASE)


# -- engine + scheduler integration (tiny engines, lazy compiles) ------------


def _cfg(**overrides):
    base = dict(vocab_size=VOCAB, seq_len=64)
    base.update(overrides)
    return standalone_gpt_config(**base)


@pytest.fixture(scope="module")
def model(devices8):
    cfg = _cfg()
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    return cfg, params, mesh


def _reqs(n, *, seed0=7000, max_tokens=10):
    out = []
    for i in range(n):
        p_len = 2 + (3 * i) % 6
        prompt = [int(t) for t in jax.random.randint(
            jax.random.PRNGKey(seed0 + i), (p_len,), 0, VOCAB)]
        sp = (SamplingParams(temperature=0.9, top_k=7, seed=seed0 + i)
              if i % 2 else SamplingParams())
        out.append(Request(f"t{i}", prompt, max_tokens=max_tokens,
                           sampling=sp))
    return out


def test_engine_ladder_validation_and_unwarmed_variant_rejection(model):
    cfg, params, mesh = model
    with pytest.raises(ValueError, match="must contain decode_chunk"):
        Engine(cfg, params, mesh, EngineConfig(
            slots=2, max_prompt_len=8, max_seq_len=24, decode_chunk=4,
            decode_chunks=(1, 2)))
    with pytest.raises(ValueError, match="strictly increasing"):
        Engine(cfg, params, mesh, EngineConfig(
            slots=2, max_prompt_len=8, max_seq_len=24,
            decode_chunks=(2, 2)))
    with pytest.raises(ValueError, match="spec_ks"):
        Engine(cfg, params, mesh, EngineConfig(
            slots=2, max_prompt_len=8, max_seq_len=24, spec_k=3,
            spec_ks=(2,)))
    with pytest.raises(ValueError, match="plain variant"):
        Engine(cfg, params, mesh, EngineConfig(
            slots=2, max_prompt_len=8, max_seq_len=24, spec_ks=(0, 2)))
    eng = Engine(cfg, params, mesh, EngineConfig(
        slots=2, max_prompt_len=8, max_seq_len=24, decode_chunk=1,
        decode_chunks=(1, 2)))
    assert eng.decode_chunks == (1, 2) and eng.spec_ks == ()
    # an unwarmed rung must raise, not compile mid-serve
    with pytest.raises(ValueError, match="pre-warmed"):
        eng.step_async(chunk=4)
    with pytest.raises(ValueError, match="spec"):
        eng.step_async(spec=True)
    with pytest.raises(ValueError, match="without spec"):
        eng.step_async(spec_k=2)
    assert "step_c1" in eng.compiled_cache_sizes()
    assert "step_c2" in eng.compiled_cache_sizes()
    eng.close()


def test_scheduler_tuner_ladder_validation(model):
    cfg, params, mesh = model
    eng = Engine(cfg, params, mesh, EngineConfig(
        slots=2, max_prompt_len=8, max_seq_len=24, decode_chunk=1,
        decode_chunks=(1, 2)))
    # a candidate outside the engine's warmed ladder fails LOUDLY at
    # construction — the runtime half of the pre-warm contract
    with pytest.raises(ValueError, match="not pre-warmed"):
        Scheduler(eng, tuner=TunerConfig(decode_chunk=(1, 2, 4)))
    with pytest.raises(ValueError, match="not pre-warmed"):
        Scheduler(eng, tuner=TunerConfig(spec_k=(0, 2)))
    with pytest.raises(ValueError, match="base"):
        Scheduler(eng, pipeline_depth=3,
                  tuner=TunerConfig(pipeline_depth=(1, 2)))
    eng.close()
    # a tuner owning spec_k replaces the gate — passing both is a
    # config error, and the auto-created gate must be absent
    eng2 = Engine(cfg, params, mesh, EngineConfig(
        slots=2, max_prompt_len=8, max_seq_len=24, spec_k=2,
        spec_hist=8))
    with pytest.raises(ValueError, match="spec_gate"):
        Scheduler(eng2, tuner=TunerConfig(spec_k=(0, 2)),
                  spec_gate=SpecGateConfig())
    sched = Scheduler(eng2, tuner=TunerConfig(spec_k=(0, 2)))
    assert sched._gate is None
    eng2.close()


class _FakeClock:
    """Deterministic scheduler clock: a tiny epsilon per read (strict
    monotonicity) plus explicit advances from the latency model."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        self.t += 1e-6
        return self.t

    def advance(self, dt):
        self.t += dt

    def sleep(self, dt):
        self.t += dt


class _TimedHandle:
    """Wrap a StepHandle so its fetch advances the fake clock by the
    injected latency model's cost for the dispatched variant."""

    def __init__(self, handle, clk, dt):
        self._handle, self._clk, self._dt = handle, clk, dt

    def fetch(self):
        self._clk.advance(self._dt)
        return self._handle.fetch()

    def __getattr__(self, name):
        return getattr(self._handle, name)


def _inject_latency(eng, clk, model):
    """Shim the engine's dispatch so every chunk's wall time comes
    from the injected model (keyed on the dispatched variant) instead
    of host noise — the fake-clock harness's device stand-in."""
    orig = eng.step_async

    def step_async(*, spec=False, chunk=None, spec_k=None):
        h = orig(spec=spec, chunk=chunk, spec_k=spec_k)
        c = chunk if chunk is not None else eng.engine_cfg.decode_chunk
        return _TimedHandle(h, clk, model(c, spec))

    eng.step_async = step_async


def test_fake_clock_scheduler_converges_and_reconverges(model):
    """The acceptance oracle, end to end on a real engine: an injected
    latency model makes chunk=2 strictly dominant (fixed per-dispatch
    overhead amortized over more tokens) — the controller converges to
    it; flipping the model to punish chunk=2 re-converges back to
    chunk=1. Every dispatched variant is pre-warmed by construction
    (step_async validates), and the per-variant compiled caches stay
    at 1 across all switching."""
    cfg, params, mesh = model
    eng = Engine(cfg, params, mesh, EngineConfig(
        slots=2, max_prompt_len=8, max_seq_len=56, decode_chunk=1,
        decode_chunks=(1, 2)))
    clk = _FakeClock()
    cost = {1: 0.011, 2: 0.012}   # ~2x tokens for ~9% more wall

    def run(reqs):
        sched = Scheduler(
            eng, clock=clk, sleep=clk.sleep, pipeline_depth=1,
            tuner=TunerConfig(decode_chunk=(1, 2), probe_every=3,
                              probe_chunks=2, min_measure_chunks=2))
        for r in reqs:
            sched.submit(r)
        sched.run_until_idle()
        return sched

    _inject_latency(eng, clk, lambda c, spec: cost[c])
    sched = run(_reqs(4, max_tokens=40))
    s = sched.summary()
    assert s["tuner_decode_chunk"] == 2.0, s
    assert s["tuner_switches"] >= 1.0
    # the shift: chunk=2 becomes 20x worse — the controller must walk
    # back to chunk=1 within the run
    eng.rebuild_slots()
    cost[2] = 0.25
    sched2 = run(_reqs(4, seed0=7100, max_tokens=40))
    s2 = sched2.summary()
    # a fresh scheduler starts from base chunk=1 and must REFUSE the
    # now-bad chunk=2 after probing it
    assert s2["tuner_decode_chunk"] == 1.0, s2
    assert s2["tuner_probes"] >= 1.0 and s2["tuner_switches"] == 0.0
    # trace stability without warmup: lazily-compiled programs hold at
    # ONE entry each across all the switching (0 = never dispatched —
    # this run never needed every admission rung)
    sizes = {k: v for k, v in eng.compiled_cache_sizes().items()
             if v is not None}
    assert all(v in (0, 1) for v in sizes.values()), sizes
    assert sizes["step_c1"] == 1 and sizes["step_c2"] == 1
    eng.close()


def test_constrained_admission_mid_tick_forces_base_chunk(model):
    """THE mask-staleness race: a constrained request admitted AFTER
    the tick-start freeze check, while the incumbent chunk is >1,
    must still decode at the BASE chunk (=1 — submit validation's
    precondition) — a wider chunk would scan tokens 2..n against a
    stale vocab mask and emit schema-invalid output. The exclusion is
    re-evaluated at dispatch, freezing the controller to base."""
    from apex_tpu.serving.api.constrain import JsonSchemaConstraint

    _, _, mesh = model
    # byte-level constraint tokens need a >=256 vocab
    cfg = _cfg(vocab_size=512, hidden_size=32, num_layers=1)
    params = gpt.init(cfg, jax.random.PRNGKey(1))
    eng = Engine(cfg, params, mesh, EngineConfig(
        slots=2, max_prompt_len=8, max_seq_len=56, decode_chunk=1,
        decode_chunks=(1, 2)))
    clk = _FakeClock()
    # chunk=2 strictly dominant → the incumbent moves off base
    _inject_latency(eng, clk, lambda c, spec: {1: 0.011, 2: 0.012}[c])
    rec = FlightRecorder(clock=lambda: 0.0)
    sched = Scheduler(
        eng, clock=clk, sleep=clk.sleep, pipeline_depth=1,
        recorder=rec,
        tuner=TunerConfig(decode_chunk=(1, 2), probe_every=3,
                          probe_chunks=2, min_measure_chunks=2))
    for r in _reqs(3, seed0=7700, max_tokens=30):
        sched.submit(r)
    sched.run_until_idle()
    assert sched.summary()["tuner_decode_chunk"] == 2.0  # off base
    # the constrained request arrives against a chunk=2 incumbent
    forced = list(b'"ab"')
    sched.submit(Request("c0", [3, 4, 5], max_tokens=12,
                         constraint=JsonSchemaConstraint(
                             {"enum": ["ab"]})))
    sched.run_until_idle()
    comp = sched.completions["c0"]
    assert comp.tokens == forced and comp.finish_reason == "stop"
    causes = {e[3][1] for e in rec.events()
              if e[2] == "tuner_freeze" and e[3][0] == "enter"}
    assert "constrained" in causes
    eng.close()


def test_gate_driven_spec_chunks_not_observed_by_tuner(model):
    """With the GATE owning speculation and the tuner owning only
    decode_chunk, speculative chunks' token counts reflect the gate's
    acceptance, not the chunk knob — they must be excluded from the
    tuner's EWMAs (every tuner_obs corresponds to a plain fetch)."""
    cfg, params, mesh = model
    eng = Engine(cfg, params, mesh, EngineConfig(
        slots=2, max_prompt_len=8, max_seq_len=48, decode_chunk=1,
        decode_chunks=(1, 2), spec_k=2, spec_hist=8))
    rec = FlightRecorder()
    sched = Scheduler(
        eng, pipeline_depth=1, recorder=rec,
        spec_gate=SpecGateConfig(probe_every=2, min_probe_chunks=1),
        tuner=TunerConfig(decode_chunk=(1, 2), probe_every=2,
                          probe_chunks=1, min_measure_chunks=1))
    for r in _reqs(4, seed0=7600, max_tokens=16):
        sched.submit(r)
    sched.run_until_idle()
    assert sched.summary()["spec_chunks"] > 0  # the gate actually ran
    fetches = [e for e in rec.events() if e[2] == "fetch"]
    plain_fetches = [e for e in fetches if not e[3][0]]
    obs = [e for e in rec.events() if e[2] == "tuner_obs"]
    assert len(obs) == len(plain_fetches) < len(fetches)
    eng.close()


def test_watchdog_tripping_probe_aborts_instead_of_livelocking(model):
    """A probe candidate whose chunks keep tripping the watchdog can
    never accumulate its window samples (tripped chunks are excluded
    from observation) — the trip must ABORT the window via a freeze,
    not leave the controller re-dispatching the pathological variant
    forever."""
    cfg, params, mesh = model
    eng = Engine(cfg, params, mesh, EngineConfig(
        slots=2, max_prompt_len=8, max_seq_len=56, decode_chunk=1,
        decode_chunks=(1, 2)))
    clk = _FakeClock()
    # chunk=2 hangs past the watchdog, chunk=1 is healthy
    _inject_latency(eng, clk, lambda c, spec: 0.9 if c == 2 else 0.01)
    rec = FlightRecorder(clock=lambda: 0.0)
    sched = Scheduler(
        eng, clock=clk, sleep=clk.sleep, pipeline_depth=1,
        recorder=rec,
        resilience=ResilienceConfig(watchdog_timeout_s=0.5),
        tuner=TunerConfig(decode_chunk=(1, 2), probe_every=2,
                          probe_chunks=2, min_measure_chunks=2))
    for r in _reqs(3, seed0=7500, max_tokens=30):
        sched.submit(r)
    sched.run_until_idle()   # the livelock regression: must terminate
    s = sched.summary()
    assert s["tuner_decode_chunk"] == 1.0  # never switched to the hang
    assert s["watchdog_trips"] >= 1.0
    causes = {e[3][1] for e in rec.events()
              if e[2] == "tuner_freeze" and e[3][0] == "enter"}
    assert "watchdog" in causes
    aborts = [e for e in rec.events()
              if e[2] == "tuner_probe" and e[3][2] == "abort"]
    assert aborts, "tripping probe window was never aborted"
    eng.close()


def test_autotuned_streams_bit_identical_incl_faults(model):
    """Stream parity across controller-driven switching: an autotuned
    run (forced frequent probing over chunk AND depth) emits
    bit-identical per-request streams to the plain fixed-config run —
    including under a seeded FaultPlan, where the controller
    hard-freezes through the rebuild/replay bracket (pinned via the
    recorded freeze causes)."""
    cfg, params, mesh = model
    ecfg = EngineConfig(slots=2, max_prompt_len=8, max_seq_len=40,
                        decode_chunk=1, decode_chunks=(1, 2))
    reqs = _reqs(6, max_tokens=12)

    def run(fault_plan, tuner, recorder=None):
        eng = Engine(cfg, params, mesh, ecfg, fault_plan=fault_plan)
        sched = Scheduler(
            eng, pipeline_depth=2, tuner=tuner, recorder=recorder,
            resilience=ResilienceConfig(backoff_base_s=0.001))
        for r in _reqs(6, max_tokens=12):
            sched.submit(r)
        sched.run_until_idle()
        toks = {rid: c.tokens for rid, c in sched.completions.items()}
        eng.close()
        return toks, sched

    fixed, _ = run(None, None)
    tn = TunerConfig(decode_chunk=(1, 2), pipeline_depth=(1, 2),
                     probe_every=1, probe_chunks=1,
                     min_measure_chunks=1)
    auto, sched = run(None, tn)
    assert auto == fixed
    assert sched.summary()["tuner_probes"] > 0
    # and under chaos: faults at two seams, streams still exact
    rec = FlightRecorder()
    plan = FaultPlan([FaultSpec("dispatch", 4, "error"),
                      FaultSpec("fetch", 9, "nan", slots=(1,))])
    chaos, sched2 = run(plan, tn, recorder=rec)
    assert len(plan.injected) == 2
    assert chaos == fixed
    causes = {e[3][1] for e in rec.events()
              if e[2] == "tuner_freeze" and e[3][0] == "enter"}
    assert "rebuild" in causes
    assert sched2.summary()["rebuilds"] >= 1.0


def test_autotuned_bundle_decision_replay(model, tmp_path):
    """An autotuned chaos run's post-mortem bundle replays its tuning
    decision sequence bit-identically from the recorded clocks — the
    stdlib replay_tuner path (no engine rebuild needed)."""
    from apex_tpu.telemetry import Registry
    from apex_tpu.telemetry.flightrec import read_bundle
    from apex_tpu.telemetry.replay import replay_tuner

    cfg, params, mesh = model
    plan = FaultPlan([FaultSpec("fetch", 7, "error")])
    eng = Engine(cfg, params, mesh, EngineConfig(
        slots=2, max_prompt_len=8, max_seq_len=40, decode_chunk=1,
        decode_chunks=(1, 2)), fault_plan=plan)
    rec = FlightRecorder()
    registry = Registry()
    sched = Scheduler(
        eng, pipeline_depth=2, recorder=rec, registry=registry,
        bundle_dir=str(tmp_path), bundle_meta={"params": {"init_seed": 0}},
        tuner=TunerConfig(decode_chunk=(1, 2), pipeline_depth=(1, 2),
                          probe_every=2, probe_chunks=1,
                          min_measure_chunks=1),
        resilience=ResilienceConfig(backoff_base_s=0.001))
    for r in _reqs(5, seed0=7200, max_tokens=14):
        sched.submit(r)
    sched.run_until_idle()
    # the tuner telemetry surface is live: state gauge + per-knob
    # incumbents pre-created for the declared ladder
    snap = registry.to_dict()
    assert "serving_tuner_state" in snap
    knob_samples = snap["serving_tuner_knob"]["samples"]
    assert {s["labels"].get("knob") for s in knob_samples} == {
        "decode_chunk", "pipeline_depth"}
    assert plan.injected and sched.bundles_written
    bundle = read_bundle(sched.bundles_written[0])
    # the bundle's config carries the ladders + base the replay needs
    assert bundle["config.json"]["scheduler"]["tuner"][
        "decode_chunk"] == [1, 2]
    assert bundle["config.json"]["scheduler"]["tuner_base"][
        "decode_chunk"] == 1
    assert bundle["config.json"]["engine"]["engine"][
        "decode_chunks"] == [1, 2]
    out = replay_tuner(bundle)
    assert out["mismatches"] == [], out["mismatches"]
    assert out["decisions_recorded"] > 0 and out["observations"] > 0
    eng.close()


# -- slow tier: warmup + armed guard across forced switching -----------------


@pytest.mark.slow
def test_tuner_recompile_guard_flat_across_switching(model):
    """The pre-warm contract under the armed guard: forced frequent
    probing across chunk, depth, admit-batch AND spec knobs — every
    dispatch rides a warmed variant, the guard never trips, every
    per-variant compiled cache holds at 1."""
    cfg, params, mesh = model
    eng = Engine(cfg, params, mesh, EngineConfig(
        slots=2, max_prompt_len=8, max_seq_len=48, decode_chunk=1,
        decode_chunks=(1, 2), spec_k=0, spec_ks=(2,), spec_hist=8))
    eng.warmup()
    # the trace is built BEFORE arming: jax.random prompt generation
    # is host tooling, not the serving loop under test
    reqs = _reqs(6, seed0=7300, max_tokens=16)
    with eng.recompile_guard():
        sched = Scheduler(
            eng, pipeline_depth=2,
            tuner=TunerConfig(decode_chunk=(1, 2),
                              pipeline_depth=(1, 2),
                              max_admit_batch=(0, 1),
                              spec_k=(0, 2),
                              probe_every=1, probe_chunks=1,
                              min_measure_chunks=1))
        for r in reqs:
            sched.submit(r)
        sched.run_until_idle()
    s = sched.summary()
    assert s["tuner_probes"] >= 4.0  # every knob got probed
    sizes = {k: v for k, v in eng.compiled_cache_sizes().items()
             if v is not None}
    assert all(v == 1 for v in sizes.values()), sizes
    # the spec cross-variants exist and were exercised via the ladder
    assert "step_spec_c1_k2" in sizes and "step_spec_c2_k2" in sizes
    eng.close()


@pytest.mark.slow
def test_autotuned_bundle_full_replay_streams_and_decisions(
        model, tmp_path):
    """The full acceptance round trip: replay_bundle on an autotuned
    chaos bundle rebuilds the engine (ladders included), re-runs the
    trace to bit-identical streams, AND reproduces the tuning decision
    sequence from the recorded clocks — one command, both verdicts."""
    from apex_tpu.telemetry.replay import replay_bundle

    cfg, params, mesh = model
    plan = FaultPlan([FaultSpec("dispatch", 6, "error")])
    eng = Engine(cfg, params, mesh, EngineConfig(
        slots=2, max_prompt_len=8, max_seq_len=40, decode_chunk=1,
        decode_chunks=(1, 2)), fault_plan=plan)
    rec = FlightRecorder()
    sched = Scheduler(
        eng, pipeline_depth=2, recorder=rec,
        bundle_dir=str(tmp_path),
        bundle_meta={"params": {"init_seed": 0}},
        tuner=TunerConfig(decode_chunk=(1, 2), probe_every=2,
                          probe_chunks=1, min_measure_chunks=1),
        resilience=ResilienceConfig(backoff_base_s=0.001))
    for r in _reqs(5, seed0=7400, max_tokens=12):
        sched.submit(r)
    sched.run_until_idle()
    assert plan.injected and sched.bundles_written
    out = replay_bundle(sched.bundles_written[0], verbose=False)
    assert out["mismatches"] == [], out["mismatches"]
    assert out["tuner"]["decisions_recorded"] > 0
    eng.close()
