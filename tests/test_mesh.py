"""Mesh topology + collectives tests.

Parity model: apex tests/L0/run_transformer/test_parallel_state.py (U)
(group math) and test_mapping.py (U) (collective fwd/bwd), rebuilt on a
CPU-simulated 8-device mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import mesh as mx


def test_build_mesh_infers_dp(devices8):
    m = mx.build_mesh(tp=2, pp=2, devices=devices8)
    assert mx.mesh_shape_of(m) == {"pp": 2, "dp": 2, "ep": 1, "cp": 1, "tp": 2}


def test_build_mesh_cp_axis(devices8):
    m = mx.build_mesh(tp=2, cp=2, devices=devices8)
    assert mx.mesh_shape_of(m) == {"pp": 1, "dp": 2, "ep": 1, "cp": 2, "tp": 2}


def test_build_mesh_rejects_bad_factorization(devices8):
    with pytest.raises(ValueError):
        mx.build_mesh(tp=3, devices=devices8)
    with pytest.raises(ValueError):
        mx.build_mesh(tp=2, pp=2, dp=4, devices=devices8)


def test_tp_innermost_axis_is_adjacent(devices8):
    # tp must vary fastest so TP collectives ride adjacent (ICI) links.
    m = mx.build_mesh(tp=4, pp=1, devices=devices8)
    ids = np.vectorize(lambda d: d.id)(m.devices)
    assert ids.shape == (1, 2, 1, 1, 4)
    assert list(ids[0, 0, 0, 0, :]) == [0, 1, 2, 3]


def test_psum_and_axis_queries(devices8):
    m = mx.build_mesh(tp=4, devices=devices8)

    def f(x):
        r = mx.axis_index("tp").astype(jnp.float32)
        return mx.psum(x + r, "tp"), mx.axis_size("tp") * jnp.ones(())

    x = jnp.ones((2, 8))
    out, size = jax.jit(
        jax.shard_map(f, mesh=m, in_specs=P(None, "tp"), out_specs=(P(None, "tp"), P()))
    )(x)
    # sum over 4 ranks of (1 + rank) = 4 + 6 = 10
    np.testing.assert_allclose(out, 10.0 * np.ones((2, 8)))
    assert int(size) == 4


def test_all_gather_reduce_scatter_roundtrip(devices8):
    m = mx.build_mesh(tp=8, devices=devices8)
    x = jnp.arange(32.0).reshape(8, 4)

    def f(shard):
        full = mx.all_gather(shard, "tp", gather_axis=0)  # (8, 4) everywhere
        return mx.reduce_scatter(full, "tp", scatter_axis=0)  # 8x-summed shard

    out = jax.jit(jax.shard_map(f, mesh=m, in_specs=P("tp"), out_specs=P("tp")))(x)
    np.testing.assert_allclose(np.asarray(out), 8.0 * np.asarray(x))


def test_ppermute_shift_ring_and_edge(devices8):
    m = mx.build_mesh(tp=8, devices=devices8)
    x = jnp.arange(8.0).reshape(8, 1)

    ring = jax.jit(
        jax.shard_map(
            lambda s: mx.ppermute_shift(s, "tp", 1, wrap=True),
            mesh=m, in_specs=P("tp"), out_specs=P("tp"),
        )
    )(x)
    np.testing.assert_allclose(np.asarray(ring).ravel(), [7, 0, 1, 2, 3, 4, 5, 6])

    edge = jax.jit(
        jax.shard_map(
            lambda s: mx.ppermute_shift(s, "tp", 1, wrap=False),
            mesh=m, in_specs=P("tp"), out_specs=P("tp"),
        )
    )(x)
    np.testing.assert_allclose(np.asarray(edge).ravel(), [0, 0, 1, 2, 3, 4, 5, 6])


def test_pbroadcast_from(devices8):
    m = mx.build_mesh(tp=8, devices=devices8)
    x = jnp.arange(8.0).reshape(8, 1)
    out = jax.jit(
        jax.shard_map(
            lambda s: mx.pbroadcast_from(s, "tp", src_index=3),
            mesh=m, in_specs=P("tp"), out_specs=P("tp"),
        )
    )(x)
    np.testing.assert_allclose(np.asarray(out).ravel(), [3.0] * 8)
