"""Mesh topology + collectives tests.

Parity model: apex tests/L0/run_transformer/test_parallel_state.py (U)
(group math) and test_mapping.py (U) (collective fwd/bwd), rebuilt on a
CPU-simulated 8-device mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import mesh as mx


def test_build_mesh_infers_dp(devices8):
    m = mx.build_mesh(tp=2, pp=2, devices=devices8)
    assert mx.mesh_shape_of(m) == {"pp": 2, "dp": 2, "ep": 1, "cp": 1, "tp": 2}


def test_build_mesh_cp_axis(devices8):
    m = mx.build_mesh(tp=2, cp=2, devices=devices8)
    assert mx.mesh_shape_of(m) == {"pp": 1, "dp": 2, "ep": 1, "cp": 2, "tp": 2}


def test_build_mesh_rejects_bad_factorization(devices8):
    with pytest.raises(ValueError):
        mx.build_mesh(tp=3, devices=devices8)
    with pytest.raises(ValueError):
        mx.build_mesh(tp=2, pp=2, dp=4, devices=devices8)


def test_tp_innermost_axis_is_adjacent(devices8):
    # tp must vary fastest so TP collectives ride adjacent (ICI) links.
    m = mx.build_mesh(tp=4, pp=1, devices=devices8)
    ids = np.vectorize(lambda d: d.id)(m.devices)
    assert ids.shape == (1, 2, 1, 1, 4)
    assert list(ids[0, 0, 0, 0, :]) == [0, 1, 2, 3]


def test_psum_and_axis_queries(devices8):
    m = mx.build_mesh(tp=4, devices=devices8)

    def f(x):
        r = mx.axis_index("tp").astype(jnp.float32)
        return mx.psum(x + r, "tp"), mx.axis_size("tp") * jnp.ones(())

    x = jnp.ones((2, 8))
    out, size = jax.jit(
        jax.shard_map(f, mesh=m, in_specs=P(None, "tp"), out_specs=(P(None, "tp"), P()))
    )(x)
    # sum over 4 ranks of (1 + rank) = 4 + 6 = 10
    np.testing.assert_allclose(out, 10.0 * np.ones((2, 8)))
    assert int(size) == 4


def test_all_gather_reduce_scatter_roundtrip(devices8):
    m = mx.build_mesh(tp=8, devices=devices8)
    x = jnp.arange(32.0).reshape(8, 4)

    def f(shard):
        full = mx.all_gather(shard, "tp", gather_axis=0)  # (8, 4) everywhere
        return mx.reduce_scatter(full, "tp", scatter_axis=0)  # 8x-summed shard

    out = jax.jit(jax.shard_map(f, mesh=m, in_specs=P("tp"), out_specs=P("tp")))(x)
    np.testing.assert_allclose(np.asarray(out), 8.0 * np.asarray(x))


def test_ppermute_shift_ring_and_edge(devices8):
    m = mx.build_mesh(tp=8, devices=devices8)
    x = jnp.arange(8.0).reshape(8, 1)

    ring = jax.jit(
        jax.shard_map(
            lambda s: mx.ppermute_shift(s, "tp", 1, wrap=True),
            mesh=m, in_specs=P("tp"), out_specs=P("tp"),
        )
    )(x)
    np.testing.assert_allclose(np.asarray(ring).ravel(), [7, 0, 1, 2, 3, 4, 5, 6])

    edge = jax.jit(
        jax.shard_map(
            lambda s: mx.ppermute_shift(s, "tp", 1, wrap=False),
            mesh=m, in_specs=P("tp"), out_specs=P("tp"),
        )
    )(x)
    np.testing.assert_allclose(np.asarray(edge).ravel(), [0, 0, 1, 2, 3, 4, 5, 6])


def test_pbroadcast_from(devices8):
    m = mx.build_mesh(tp=8, devices=devices8)
    x = jnp.arange(8.0).reshape(8, 1)
    out = jax.jit(
        jax.shard_map(
            lambda s: mx.pbroadcast_from(s, "tp", src_index=3),
            mesh=m, in_specs=P("tp"), out_specs=P("tp"),
        )
    )(x)
    np.testing.assert_allclose(np.asarray(out).ravel(), [3.0] * 8)


def test_hybrid_mesh_placement(devices8):
    """2 emulated slices x 4 chips: dp factors as dcn_dp=2 x dp_ici=2 with
    each contiguous ici block of the dp axis on one slice; tp never
    crosses a slice boundary (SURVEY.md §5 ICI/DCN mapping)."""
    m = mx.build_hybrid_mesh(tp=2, dcn_dp=2, num_slices=2,
                             devices=devices8)
    assert mx.mesh_shape_of(m) == {"pp": 1, "dp": 4, "ep": 1, "cp": 1,
                                   "tp": 2}
    ids = np.vectorize(lambda d: d.id)(m.devices)[0, :, 0, 0, :]  # [dp, tp]
    # dp 0-1 (ici part of dcn block 0) on slice 0 = devices 0..3
    assert set(ids[:2].ravel()) == {0, 1, 2, 3}
    assert set(ids[2:].ravel()) == {4, 5, 6, 7}
    # every tp pair stays within one slice
    for row in ids:
        assert (row < 4).all() or (row >= 4).all()


def test_hybrid_mesh_pp_over_dcn(devices8):
    m = mx.build_hybrid_mesh(tp=2, dcn_pp=2, num_slices=2,
                             devices=devices8)
    assert mx.mesh_shape_of(m)["pp"] == 2
    ids = np.vectorize(lambda d: d.id)(m.devices)
    assert (ids[0] < 4).all() and (ids[1] >= 4).all()  # stages = slices


def test_hybrid_mesh_validation(devices8):
    with pytest.raises(ValueError, match="slice count"):
        mx.build_hybrid_mesh(dcn_dp=4, num_slices=2, devices=devices8)
    with pytest.raises(ValueError, match="slices"):
        mx.build_hybrid_mesh(num_slices=3, devices=devices8)


def test_hybrid_mesh_trains(devices8):
    """A full train step runs unchanged over the hybrid mesh (it is just
    a Mesh with interconnect-aware placement)."""
    from apex_tpu.amp import ScalerConfig
    from apex_tpu.models import training
    from apex_tpu.optimizers import fused_adam
    from apex_tpu.transformer.testing import standalone_gpt_config

    cfg = standalone_gpt_config()
    mesh = mx.build_hybrid_mesh(tp=2, dcn_dp=2, num_slices=2,
                                devices=devices8)
    init_fn, step_fn = training.make_train_step(
        cfg, mesh, fused_adam(1e-3, layout="tree"),
        ScalerConfig(enabled=False))
    state = init_fn(jax.random.PRNGKey(0))
    tok = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 256)
    state, m = step_fn(state, tok, tok)
    assert np.isfinite(float(m["loss"]))
