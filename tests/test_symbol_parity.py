"""Symbol-level parity sweep: every key symbol SURVEY.md §2 names must be
importable (aliased where the reference's name is CUDA-flavoured), and the
call-shape parity classes must behave."""

import jax
import jax.numpy as jnp
import numpy as np


def test_survey_symbols_importable():
    from apex_tpu import fp16_utils, multi_tensor, normalization
    from apex_tpu.optimizers import (  # noqa: F401
        DistributedFusedAdam,
        DistributedFusedLAMB,
        FusedAdagrad,
        FusedAdam,
        FusedLAMB,
        FusedMixedPrecisionLamb,
        FusedNovoGrad,
        FusedSGD,
    )
    # the package-level path is apex's canonical import location
    from apex_tpu.transformer.tensor_parallel import (  # noqa: F401
        get_cuda_rng_tracker,
        set_tensor_model_parallel_attributes,
    )

    assert normalization.MixedFusedRMSNorm is normalization.fused_rms_norm
    assert fp16_utils.FP16Model is fp16_utils.fp16_model
    assert multi_tensor.MultiTensorApply


def test_multi_tensor_apply_call_shape():
    from apex_tpu.kernels.flat_ops import scale_flat
    from apex_tpu.multi_tensor import MultiTensorApply

    mta = MultiTensorApply(2048 * 32)
    tensors = [jnp.ones((33,)), jnp.full((7, 5), 2.0)]

    # the canonical composition: a flat_ops sweep returning
    # (buffers, found_inf) — the aux flag passes through
    (scaled,), found_inf = mta(scale_flat, None, [tensors], 3.0)
    np.testing.assert_allclose(np.asarray(scaled[0]), 3.0)
    np.testing.assert_allclose(np.asarray(scaled[1]), 6.0)
    assert scaled[1].shape == (7, 5)
    assert not bool(found_inf)

    # bare-buffer return normalises too (single dtype group)
    (doubled,) = mta(lambda bufs, s: bufs[0] * s, None, [tensors], 2.0)
    np.testing.assert_allclose(np.asarray(doubled[0]), 2.0)

    import pytest

    # regrouping ops are rejected with a clear error
    with pytest.raises(ValueError, match="dtype"):
        mta(lambda bufs: [bufs[0], bufs[0]], None, [tensors])
    # apex's mutated overflow buffer has no functional equivalent
    with pytest.raises(NotImplementedError, match="found_inf"):
        mta(scale_flat, jnp.zeros((1,), jnp.int32), [tensors], 1.0)


def test_set_tensor_model_parallel_attributes():
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer.tensor_parallel.layers import (
        param_is_tensor_parallel,
        set_tensor_model_parallel_attributes,
    )

    spec = set_tensor_model_parallel_attributes(P(None, None), True, 1)
    assert spec == P(None, "tp")
    assert param_is_tensor_parallel(spec)
    assert set_tensor_model_parallel_attributes(P(None), False, 0) == P(None)


def test_fp16_model_wrapper():
    from apex_tpu.fp16_utils import fp16_model

    params = {"w": jnp.ones((4, 4)), "ln": {"scale": jnp.ones((4,))}}

    def apply_fn(p, x):
        return x @ p["w"] * p["ln"]["scale"]

    wrapped, half = fp16_model(apply_fn, params, jnp.bfloat16)
    assert half["w"].dtype == jnp.bfloat16
    assert half["ln"]["scale"].dtype == jnp.float32  # norm stays fp32
    y = wrapped(half, jnp.ones((2, 4)))
    # fp32 norm affine promotes the output — the half cast shows in values
    assert y.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(y), 4.0)
    # inputs really are cast: a value not representable in bf16 rounds
    y2 = wrapped(half, jnp.full((2, 4), 1.0 + 2.0 ** -10, jnp.float32))
    np.testing.assert_allclose(np.asarray(y2), 4.0)  # 1+2^-10 -> 1 in bf16

    # pytree inputs cast too (the torch FP16Model only saw positional
    # tensors; jax apply fns commonly take batch dicts)
    def apply_dict(p, batch):
        return batch["x"] @ p["w"]

    wrapped2, half2 = fp16_model(apply_dict, params, jnp.bfloat16)
    y3 = wrapped2(half2, {"x": jnp.full((2, 4), 1.0 + 2.0 ** -10,
                                        jnp.float32)})
    assert y3.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(y3.astype(jnp.float32)), 4.0)


def test_name_layer_additions():
    """Second parity sweep: symbols at apex's canonical locations."""
    from apex_tpu.amp import load_state_dict, master_params, state_dict
    from apex_tpu.parallel import convert_syncbn_model
    from apex_tpu.transformer.log_util import (
        get_transformer_logger,
        set_logging_level,
    )
    from apex_tpu.transformer.microbatches import setup_microbatch_calculator
    from apex_tpu.transformer.tensor_parallel import broadcast_data  # noqa: F401

    # amp state round-trip
    from apex_tpu.amp import ScalerConfig
    st = ScalerConfig().init()
    assert load_state_dict(state_dict(st)).loss_scale == st.loss_scale
    # master_params: passthrough for plain trees, attribute for O2 states
    tree = {"w": jnp.ones(3)}
    assert master_params(tree) is tree

    class S:
        master_params = tree
    assert master_params(S()) is tree

    # convert_syncbn_model on a layer and a model config
    from apex_tpu.mesh.topology import AXIS_DP
    from apex_tpu.models.resnet import ResNetConfig
    from apex_tpu.parallel import SyncBatchNorm
    bn = SyncBatchNorm(8, axis=None)
    assert convert_syncbn_model(bn).axis == AXIS_DP
    cfg = ResNetConfig()
    assert convert_syncbn_model(cfg).bn_axis == AXIS_DP
    import pytest as _pytest
    with _pytest.raises(TypeError):
        convert_syncbn_model(object())

    # microbatch factory — apex's 5-arg signature (leading rank)
    calc = setup_microbatch_calculator(0, None, 64, 8, 2)
    assert calc.get() == 4

    # logging namespace
    set_logging_level("DEBUG")
    assert get_transformer_logger("x").name.startswith("apex_tpu.transformer")
