"""End-to-end example smoke: the GPT trainer script with the native data
loader, .atck checkpointing, and metrics logging on a tp=2 x dp=4 mesh —
the reference's L1 'main_amp.py actually runs' leg (SURVEY.md §4), in
subprocess form so the script's own entry path is what's tested."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest


def test_gpt_train_example_end_to_end(tmp_path):
    data = str(tmp_path / "toks.bin")
    rng = np.random.default_rng(0)
    from apex_tpu import data as atdata
    atdata.write_token_file(data, rng.integers(0, 1024, 200_000,
                                               dtype=np.int64).astype(np.int32),
                            seq_len=128)
    ckpt = str(tmp_path / "ck")
    metrics = str(tmp_path / "m.jsonl")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update(PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               PYTHONPATH=repo + os.pathsep + env.get("PYTHONPATH", ""))
    cmd = [sys.executable, os.path.join(repo, "examples", "gpt_train.py"),
           "--preset", "tiny", "--tp", "2", "--steps", "2",
           "--clip-grad-norm", "1.0",
           "--data", data, "--ckpt", ckpt, "--metrics", metrics]
    r = subprocess.run(cmd, env=env, capture_output=True, text=True,
                       timeout=900)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "saved" in r.stdout
    lines = [json.loads(l) for l in open(metrics)]
    assert len(lines) == 2 and np.isfinite(lines[-1]["loss"])
    assert lines[-1]["grad_norm"] > 0  # clip flag flows through the step

    # resume leg: picks up the saved step counter
    cmd2 = list(cmd)
    cmd2[cmd2.index("--steps") + 1] = "1"
    r2 = subprocess.run(cmd2, env=env, capture_output=True, text=True,
                        timeout=900)
    assert r2.returncode == 0, r2.stderr[-2000:]
    assert "resumed" in r2.stdout and "at step 2" in r2.stdout


def test_retinanet_example_smoke(tmp_path):
    """BASELINE config #3: SyncBN + FusedSGD + focal loss detection slice
    runs end-to-end on the simulated mesh with a decreasing loss."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update(PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               PYTHONPATH=repo + os.pathsep + env.get("PYTHONPATH", ""))
    cmd = [sys.executable,
           os.path.join(repo, "examples", "retinanet_detect.py"),
           "--steps", "2", "--batch", "1", "--image", "32",
           "--classes", "4", "--depth", "26"]
    r = subprocess.run(cmd, env=env, capture_output=True, text=True,
                       timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    losses = [float(l.split("loss ")[1].split(" ")[0])
              for l in r.stdout.splitlines() if l.startswith("step ")]
    assert len(losses) == 2 and losses[1] < losses[0]


def test_imagenet_example_smoke(tmp_path):
    """BASELINE config #1: ResNet + bf16-policy + DP grad pmean +
    FusedSGD runs end-to-end on the simulated mesh."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update(PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               PYTHONPATH=repo + os.pathsep + env.get("PYTHONPATH", ""))
    cmd = [sys.executable, os.path.join(repo, "examples", "imagenet_amp.py"),
           "--steps", "2", "--batch", "8", "--image", "32", "--depth", "26"]
    r = subprocess.run(cmd, env=env, capture_output=True, text=True,
                       timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    losses = [float(l.rsplit(" ", 1)[1])
              for l in r.stdout.splitlines() if l.startswith("step ")]
    assert len(losses) == 2 and losses[1] < losses[0]


@pytest.mark.slow
def test_imagenet_example_native_loader(tmp_path):
    """Config #1 with the native ImageLoader path: packed uint8 records →
    prefetch thread → on-device normalization (different batches per step,
    so only completion is asserted).

    Marked ``slow`` by the tier-1 marker audit (conftest): ~58 s solo
    on the CPU mesh, over the ~60 s per-test budget under full-suite
    load. The cheaper ``test_imagenet_example_smoke`` keeps the
    e2e path in tier-1; this native-loader variant runs in the soak
    tier."""
    from apex_tpu import data as atdata

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update(PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               PYTHONPATH=repo + os.pathsep + env.get("PYTHONPATH", ""))
    rng = np.random.default_rng(3)
    img_file = str(tmp_path / "train.bin")
    atdata.write_image_file(
        img_file, rng.integers(0, 256, (24, 32, 32, 3), dtype=np.uint8),
        rng.integers(0, 1000, 24))
    ck = str(tmp_path / "rn.atck")
    cmd = [sys.executable, os.path.join(repo, "examples", "imagenet_amp.py"),
           "--steps", "2", "--batch", "8", "--image", "32", "--depth", "26",
           "--data", img_file, "--val-data", img_file, "--val-batches", "2",
           "--ckpt", ck]
    r = subprocess.run(cmd, env=env, capture_output=True, text=True,
                       timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "images/s" in r.stdout
    assert "prec@1" in r.stdout and "over 16 images" in r.stdout
    assert "saved" in r.stdout

    r2 = subprocess.run(cmd, env=env, capture_output=True, text=True,
                        timeout=600)
    assert r2.returncode == 0, r2.stderr[-2000:]
    assert "resumed" in r2.stdout and "at step 2" in r2.stdout
    assert "step 3 loss" in r2.stdout  # counter continues past the resume


def test_simple_distributed_example_smoke(tmp_path):
    """The reference's examples/simple/distributed demo (U): amp O2
    fp16 + dynamic scaler + DDP grad reduce, smallest-possible loop;
    loss must fall and the dynamic scale must be reported."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update(PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               PYTHONPATH=repo + os.pathsep + env.get("PYTHONPATH", ""))
    cmd = [sys.executable,
           os.path.join(repo, "examples", "simple_distributed.py"),
           "--steps", "3", "--batch", "16", "--dim", "64", "--fp16"]
    r = subprocess.run(cmd, env=env, capture_output=True, text=True,
                       timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    steps = [l for l in r.stdout.splitlines() if l.startswith("step ")]
    losses = [float(l.split("loss ")[1].split(" ")[0]) for l in steps]
    assert len(losses) == 3 and losses[-1] < losses[0]
    assert all("scale 65536" in l for l in steps)  # fp16 dynamic scaler on


def test_gpt_train_moe_example_smoke(tmp_path):
    """--experts/--ep flag plumbing: MoE-GPT over ep=2 x tp=2 trains with
    a falling loss through the flagship example."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update(PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               PYTHONPATH=repo + os.pathsep + env.get("PYTHONPATH", ""))
    cmd = [sys.executable, os.path.join(repo, "examples", "gpt_train.py"),
           "--preset", "tiny", "--experts", "4", "--ep", "2", "--tp", "2",
           "--steps", "2", "--batch", "8"]
    r = subprocess.run(cmd, env=env, capture_output=True, text=True,
                       timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    losses = [float(l.rsplit(" ", 1)[1])
              for l in r.stdout.splitlines() if l.startswith("step ")]
    assert len(losses) == 2 and losses[1] < losses[0]


def test_serve_gpt_example_smoke(tmp_path):
    """Offline batch serving: a JSONL request file (greedy, sampled, and
    an eos-terminal prompt) flows through the continuous-batching engine
    over tp=2; one line per request plus a summary JSON line."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update(PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               PYTHONPATH=repo + os.pathsep + env.get("PYTHONPATH", ""))
    reqfile = str(tmp_path / "requests.jsonl")
    with open(reqfile, "w") as f:
        for d in ({"id": "greedy", "prompt": [3, 1, 4, 1, 5],
                   "max_tokens": 4},
                  {"id": "sampled", "prompt": [2, 7, 1, 8],
                   "max_tokens": 5, "temperature": 0.9, "top_k": 11,
                   "seed": 9},
                  {"id": "instant", "prompt": [6, 2, 9],
                   "max_tokens": 6, "eos_token_id": 9}):
            f.write(json.dumps(d) + "\n")
    cmd = [sys.executable, os.path.join(repo, "examples", "serve_gpt.py"),
           "--tp", "2", "--slots", "2", "--requests", reqfile]
    r = subprocess.run(cmd, env=env, capture_output=True, text=True,
                       timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = {l.split()[1]: l for l in r.stdout.splitlines()
             if l.startswith("request ")}
    assert set(lines) == {"greedy", "sampled", "instant"}
    assert "[length]" in lines["greedy"]
    # the eos-terminal prompt completes at submit with zero tokens
    assert "[eos]" in lines["instant"] and "-> []" in lines["instant"]
    served = [l for l in r.stdout.splitlines() if l.startswith("served ")]
    summary = json.loads(served[0][len("served "):])
    assert summary["requests_completed"] == 3
    assert summary["tokens_emitted"] == 9  # 4 + 5 + 0


def test_generate_example_smoke(tmp_path):
    """Decode demo runs greedy over tp=2 and prints a continuation per
    batch row."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update(PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               PYTHONPATH=repo + os.pathsep + env.get("PYTHONPATH", ""))
    cmd = [sys.executable, os.path.join(repo, "examples", "generate.py"),
           "--tp", "2", "--n-new", "4", "--batch", "2"]
    r = subprocess.run(cmd, env=env, capture_output=True, text=True,
                       timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [l for l in r.stdout.splitlines() if l.startswith("prompt ")]
    assert len(lines) == 2 and all("->" in l for l in lines)
