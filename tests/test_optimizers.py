"""Fused optimizer tests.

Oracle pattern per apex tests/L0/run_optimizers (U): run the fused
optimizer and a reference implementation (torch.optim on CPU — the same
oracle apex compares against) over random params/grads for several steps
and compare trajectories with per-dtype tolerances.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu import optimizers as opt
from apex_tpu.contrib import clip_grad_norm_


def make_tree(key, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "w": jax.random.normal(k1, (7, 13), dtype),
        "b": jax.random.normal(k2, (13,), dtype),
        "emb": jax.random.normal(k3, (3, 5), dtype),
    }


def tree_to_torch(tree):
    return [torch.tensor(np.asarray(v, np.float32), requires_grad=True)
            for v in jax.tree.leaves(tree)]


def assert_trees_close(jtree, torch_params, rtol=1e-5, atol=1e-5):
    for jv, tv in zip(jax.tree.leaves(jtree), torch_params):
        np.testing.assert_allclose(
            np.asarray(jv, np.float32), tv.detach().numpy(), rtol=rtol, atol=atol)


def run_both(tx, torch_opt_fn, n_steps=5, seed=0):
    key = jax.random.PRNGKey(seed)
    params = make_tree(key)
    tparams = tree_to_torch(params)
    topt = torch_opt_fn(tparams)
    state = tx.init(params)
    step = jax.jit(lambda g, s, p: tx.step(g, s, p))
    for i in range(n_steps):
        gkey = jax.random.fold_in(key, i)
        grads = jax.tree.map(
            lambda p, k=gkey: jax.random.normal(k, p.shape, p.dtype), params)
        params, state = step(grads, state, params)
        for tp, gv in zip(tparams, jax.tree.leaves(grads)):
            tp.grad = torch.tensor(np.asarray(gv, np.float32))
        topt.step()
    return params, tparams


class TestFusedAdam:
    def test_matches_torch_adamw(self):
        tx = opt.fused_adam(1e-2, b1=0.9, b2=0.999, eps=1e-8,
                            weight_decay=0.1, adam_w_mode=True)
        params, tparams = run_both(
            tx, lambda ps: torch.optim.AdamW(ps, lr=1e-2, betas=(0.9, 0.999),
                                             eps=1e-8, weight_decay=0.1))
        assert_trees_close(params, tparams, rtol=1e-4, atol=1e-5)

    def test_matches_torch_adam_l2_mode(self):
        tx = opt.fused_adam(3e-3, weight_decay=0.05, adam_w_mode=False)
        params, tparams = run_both(
            tx, lambda ps: torch.optim.Adam(ps, lr=3e-3, weight_decay=0.05))
        assert_trees_close(params, tparams, rtol=1e-4, atol=1e-5)

    def test_update_plus_apply_equals_step(self):
        key = jax.random.PRNGKey(1)
        params = make_tree(key)
        grads = jax.tree.map(lambda p: p * 0.1, params)
        tx = opt.fused_adam(1e-2, weight_decay=0.01)
        state = tx.init(params)
        upd, s1 = tx.update(grads, state, params)
        applied = jax.tree.map(lambda p, u: p + u, params, upd)
        stepped, s2 = tx.step(grads, state, params)
        assert_trees_close(applied, tree_to_torch(stepped), rtol=1e-6, atol=1e-6)
        for a, b in zip(jax.tree.leaves(s1.m), jax.tree.leaves(s2.m)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b))

    def test_grad_scale_folds_unscale(self):
        """step(grads*S, grad_scale=1/S) == step(grads) — the amp pipeline."""
        key = jax.random.PRNGKey(2)
        params = make_tree(key)
        grads = jax.tree.map(lambda p: p * 0.3, params)
        tx = opt.fused_adam(1e-2)
        state = tx.init(params)
        a, _ = tx.step(grads, state, params)
        scaled = jax.tree.map(lambda g: g * 1024.0, grads)
        b, _ = tx.step(scaled, state, params, grad_scale=1.0 / 1024.0)
        assert_trees_close(a, tree_to_torch(b), rtol=1e-6, atol=1e-6)

    def test_lr_schedule_traced(self):
        sched = lambda count: 1e-2 / count.astype(jnp.float32)
        tx = opt.fused_adam(sched)
        params = make_tree(jax.random.PRNGKey(3))
        grads = jax.tree.map(jnp.ones_like, params)
        state = tx.init(params)
        step = jax.jit(lambda g, s, p: tx.step(g, s, p))
        p1, state = step(grads, state, params)
        p2, state = step(grads, state, p1)
        # lr halves on the second step; moves must differ
        d1 = np.abs(np.asarray(p1["b"]) - np.asarray(params["b"])).mean()
        d2 = np.abs(np.asarray(p2["b"]) - np.asarray(p1["b"])).mean()
        assert d2 < d1

    def test_mixed_dtype_params(self):
        key = jax.random.PRNGKey(4)
        params = {
            "f32": jax.random.normal(key, (9, 4)),
            "bf16": jax.random.normal(key, (5, 5), jnp.bfloat16),
        }
        tx = opt.fused_adam(1e-2)
        state = tx.init(params)
        grads = jax.tree.map(lambda p: jnp.ones_like(p) * 0.5, params)
        new_p, _ = jax.jit(lambda g, s, p: tx.step(g, s, p))(grads, state, params)
        assert new_p["bf16"].dtype == jnp.bfloat16
        assert new_p["f32"].dtype == jnp.float32
        assert not np.allclose(np.asarray(new_p["f32"]), np.asarray(params["f32"]))


class TestFusedSGD:
    @pytest.mark.parametrize("momentum,nesterov,wd", [
        (0.0, False, 0.0), (0.9, False, 1e-4), (0.9, True, 0.0)])
    def test_matches_torch_sgd(self, momentum, nesterov, wd):
        tx = opt.fused_sgd(1e-2, momentum=momentum, nesterov=nesterov,
                           weight_decay=wd)
        params, tparams = run_both(
            tx, lambda ps: torch.optim.SGD(ps, lr=1e-2, momentum=momentum,
                                           nesterov=nesterov, weight_decay=wd))
        assert_trees_close(params, tparams, rtol=1e-5, atol=1e-6)

    def test_dampening_first_step_matches_torch(self):
        tx = opt.fused_sgd(1e-1, momentum=0.9, dampening=0.3)
        params, tparams = run_both(
            tx, lambda ps: torch.optim.SGD(ps, lr=1e-1, momentum=0.9,
                                           dampening=0.3), n_steps=3)
        assert_trees_close(params, tparams, rtol=1e-5, atol=1e-6)


class TestFusedAdagrad:
    def test_matches_torch_adagrad(self):
        tx = opt.fused_adagrad(5e-2, eps=1e-10, weight_decay=0.01)
        params, tparams = run_both(
            tx, lambda ps: torch.optim.Adagrad(ps, lr=5e-2, eps=1e-10,
                                               weight_decay=0.01))
        assert_trees_close(params, tparams, rtol=1e-5, atol=1e-6)


def ref_lamb_step(params, grads, m, v, count, *, lr, b1, b2, eps, wd,
                  max_grad_norm, grad_averaging=True):
    """Hand-written NVLAMB reference (apex FusedLAMB semantics)."""
    leaves = jax.tree.leaves(params)
    gleaves = jax.tree.leaves(grads)
    gnorm = float(np.sqrt(sum(float((np.asarray(g, np.float64) ** 2).sum())
                              for g in gleaves)))
    clip = min(1.0, max_grad_norm / (gnorm + 1e-6))
    new_p, new_m, new_v = [], [], []
    bc1 = 1 - b1 ** count
    bc2 = 1 - b2 ** count
    for p, g, mi, vi in zip(leaves, gleaves, m, v):
        p = np.asarray(p, np.float64)
        g = np.asarray(g, np.float64) * clip
        mi = b1 * mi + ((1 - b1) if grad_averaging else 1.0) * g
        vi = b2 * vi + (1 - b2) * g * g
        u = (mi / bc1) / (np.sqrt(vi / bc2) + eps) + wd * p
        pn = np.linalg.norm(p)
        un = np.linalg.norm(u)
        ratio = pn / un if (pn > 0 and un > 0) else 1.0
        new_p.append(p - lr * ratio * u)
        new_m.append(mi)
        new_v.append(vi)
    return new_p, new_m, new_v


class TestFusedLAMB:
    @pytest.mark.parametrize("grad_averaging,lay", [
        (True, "flat"), (True, "tree"), (False, "flat"), (False, "tree")])
    def test_matches_reference(self, grad_averaging, lay):
        key = jax.random.PRNGKey(5)
        params = make_tree(key)
        tx = opt.fused_lamb(1e-2, weight_decay=0.01, max_grad_norm=1.0,
                            grad_averaging=grad_averaging, layout=lay)
        state = tx.init(params)
        leaves = jax.tree.leaves(params)
        m = [np.zeros(np.asarray(l).shape) for l in leaves]
        v = [np.zeros(np.asarray(l).shape) for l in leaves]
        ref_p = [np.asarray(l, np.float64) for l in leaves]
        step = jax.jit(lambda g, s, p: tx.step(g, s, p))
        for i in range(3):
            gkey = jax.random.fold_in(key, 100 + i)
            grads = jax.tree.map(
                lambda p, k=gkey: jax.random.normal(k, p.shape, p.dtype), params)
            params, state = step(grads, state, params)
            ref_tree = jax.tree.unflatten(jax.tree.structure(grads), ref_p)
            ref_p, m, v = ref_lamb_step(
                ref_tree, grads, m, v, i + 1,
                lr=1e-2, b1=0.9, b2=0.999, eps=1e-6, wd=0.01,
                max_grad_norm=1.0, grad_averaging=grad_averaging)
        for got, want in zip(jax.tree.leaves(params), ref_p):
            np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-5)

    @pytest.mark.parametrize("lay", ["flat", "tree"])
    def test_grad_averaging_knob_is_live(self, lay):
        """ONE step from the same fresh state with the knob on vs off
        must differ: the trust ratio cancels the uniform 1/(1-b1)
        scaling, but the wd*p term keeps the directions distinct."""
        params = make_tree(jax.random.PRNGKey(16))
        grads = jax.tree.map(
            lambda p: jax.random.normal(
                jax.random.PRNGKey(17), p.shape, p.dtype), params)
        outs = {}
        for ga in (True, False):
            tx = opt.fused_lamb(1e-2, weight_decay=0.01,
                                grad_averaging=ga, layout=lay)
            outs[ga], _ = jax.jit(
                lambda g, s, p, t=tx: t.step(g, s, p))(
                    grads, tx.init(params), params)
        assert any(
            not np.allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
            for a, b in zip(jax.tree.leaves(outs[True]),
                            jax.tree.leaves(outs[False])))


class TestFusedNovoGrad:
    def test_runs_and_descends(self):
        key = jax.random.PRNGKey(6)
        x = jax.random.normal(key, (32, 4))
        w_true = jnp.array([[1.0], [2.0], [-1.0], [0.5]])
        y = x @ w_true
        params = {"w": jnp.zeros((4, 1))}
        tx = opt.fused_novograd(1e-1, weight_decay=0.0)
        state = tx.init(params)

        def loss_fn(p):
            return jnp.mean((x @ p["w"] - y) ** 2)

        @jax.jit
        def step(p, s):
            l, g = jax.value_and_grad(loss_fn)(p)
            p, s = tx.step(g, s, p)
            return l, p, s

        losses = []
        for _ in range(150):
            l, params, state = step(params, state)
            losses.append(float(l))
        assert losses[-1] < losses[0] * 0.1

    def test_per_tensor_second_moment_shape(self):
        params = make_tree(jax.random.PRNGKey(7))
        tx = opt.fused_novograd(1e-2)
        state = tx.init(params)
        assert state.v.shape == (3,)


class TestLARC:
    def test_clip_mode_never_amplifies(self):
        params = {"w": jnp.ones((4, 4)) * 2.0}
        grads = {"w": jnp.ones((4, 4)) * 1e-6}
        out = opt.larc_transform(grads, params, learning_rate=0.1,
                                 trust_coefficient=0.02, clip=True)
        # tiny grads → adaptive rate clips at 1 → grads unchanged
        np.testing.assert_allclose(np.asarray(out["w"]), np.asarray(grads["w"]),
                                   rtol=1e-6)

    def test_scales_large_grads_down(self):
        params = {"w": jnp.ones((4, 4)) * 0.1}
        grads = {"w": jnp.ones((4, 4)) * 100.0}
        out = opt.larc_transform(grads, params, learning_rate=0.1,
                                 trust_coefficient=0.02, clip=True)
        assert np.abs(np.asarray(out["w"])).max() < 100.0

    def test_zero_param_passthrough(self):
        params = {"w": jnp.zeros((4,))}
        grads = {"w": jnp.ones((4,))}
        out = opt.larc_transform(grads, params, learning_rate=0.1)
        np.testing.assert_allclose(np.asarray(out["w"]), np.asarray(grads["w"]))


class TestClipGrad:
    def test_clips_to_max_norm(self):
        grads = {"a": jnp.full((8,), 3.0), "b": jnp.full((4, 4), -2.0)}
        clipped, total = clip_grad_norm_(grads, 1.0)
        want_total = float(np.sqrt(8 * 9 + 16 * 4))
        np.testing.assert_allclose(float(total), want_total, rtol=1e-5)
        new_norm = float(np.sqrt(sum(
            (np.asarray(v, np.float64) ** 2).sum()
            for v in jax.tree.leaves(clipped))))
        np.testing.assert_allclose(new_norm, 1.0, rtol=1e-4)

    def test_small_grads_untouched(self):
        grads = {"a": jnp.full((8,), 1e-3)}
        clipped, _ = clip_grad_norm_(grads, 1.0)
        np.testing.assert_allclose(np.asarray(clipped["a"]),
                                   np.asarray(grads["a"]), rtol=1e-6)


class TestFlatOps:
    def test_scale_detects_overflow(self):
        from apex_tpu import multi_tensor as mt
        from apex_tpu.kernels.flat_ops import scale_flat
        good, _ = mt.pack({"a": jnp.ones((300,))})
        _, flag = scale_flat(good, 2.0)
        assert not bool(flag)
        bad, _ = mt.pack({"a": jnp.array([1.0, np.inf] * 150)})
        outs, flag = scale_flat(bad, 0.5)
        assert bool(flag)

    def test_axpby(self):
        from apex_tpu import multi_tensor as mt
        from apex_tpu.kernels.flat_ops import axpby_flat
        xb, layout = mt.pack({"a": jnp.full((200,), 2.0)})
        yb, _ = mt.pack({"a": jnp.full((200,), 3.0)})
        outs, flag = axpby_flat(2.0, xb, -1.0, yb)
        tree = mt.unpack(outs, layout)
        np.testing.assert_allclose(np.asarray(tree["a"]), np.ones(200))
        assert not bool(flag)

    def test_l2norm(self):
        from apex_tpu import multi_tensor as mt
        from apex_tpu.kernels.flat_ops import l2norm_flat
        bufs, _ = mt.pack({"a": jnp.full((100,), 2.0), "b": jnp.ones((44,))})
        got = float(l2norm_flat(bufs))
        np.testing.assert_allclose(got, np.sqrt(400 + 44), rtol=1e-6)


class TestTreeLayoutAdam:
    """layout="tree": leafwise XLA fusion, identical math to the flat
    Pallas sweep (and therefore to torch.optim.AdamW)."""

    def test_matches_torch_adamw(self):
        tx = opt.fused_adam(1e-2, b1=0.9, b2=0.999, eps=1e-8,
                            weight_decay=0.1, adam_w_mode=True,
                            layout="tree")
        params, tparams = run_both(
            tx, lambda ps: torch.optim.AdamW(ps, lr=1e-2, betas=(0.9, 0.999),
                                             eps=1e-8, weight_decay=0.1))
        assert_trees_close(params, tparams, rtol=2e-5, atol=2e-5)

    def test_matches_flat_layout(self):
        key = jax.random.PRNGKey(3)
        params = make_tree(key)
        grads = jax.tree.map(
            lambda p: jax.random.normal(jax.random.fold_in(key, 9),
                                        p.shape, p.dtype), params)
        out = {}
        for lay in ("flat", "tree"):
            tx = opt.fused_adam(1e-2, weight_decay=0.05, layout=lay)
            state = tx.init(params)
            p, state = jax.jit(tx.step)(grads, state, params)
            p, _ = jax.jit(tx.step)(grads, state, p)
            out[lay] = p
        for a, b in zip(jax.tree.leaves(out["flat"]), jax.tree.leaves(out["tree"])):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32),
                                       rtol=2e-5, atol=2e-6)

    def test_state_pspecs_mirrors_params(self):
        from jax.sharding import PartitionSpec as P
        tx = opt.fused_adam(layout="tree")
        specs = tx.state_pspecs({"w": P("tp", None), "b": P(None)})
        assert specs.count == P()
        assert specs.m == {"w": P("tp", None), "b": P(None)}
        assert specs.v == {"w": P("tp", None), "b": P(None)}

    def test_unknown_layout_rejected(self):
        with pytest.raises(ValueError):
            opt.fused_adam(layout="nope")

    def test_tuple_container_params(self):
        """Params pytrees may contain tuple *containers* — the leafwise
        unzip must transpose structurally, not by spotting 3-tuples."""
        params = (jnp.ones((4,)), jnp.ones((3,)), jnp.ones((2,)))
        tx = opt.fused_adam(1e-2, layout="tree")
        state = tx.init(params)
        grads = jax.tree.map(jnp.ones_like, params)
        p2, state = jax.jit(tx.step)(grads, state, params)
        assert [x.shape for x in p2] == [(4,), (3,), (2,)]
        assert [x.shape for x in state.m] == [(4,), (3,), (2,)]


class TestTreeLayoutSGD:
    @pytest.mark.parametrize("momentum,nesterov,wd", [
        (0.0, False, 0.0), (0.9, False, 1e-2), (0.9, True, 0.0)])
    def test_matches_torch_sgd(self, momentum, nesterov, wd):
        tx = opt.fused_sgd(1e-2, momentum=momentum, nesterov=nesterov,
                           weight_decay=wd, layout="tree")
        params, tparams = run_both(
            tx, lambda ps: torch.optim.SGD(ps, lr=1e-2, momentum=momentum,
                                           nesterov=nesterov,
                                           weight_decay=wd))
        assert_trees_close(params, tparams, rtol=2e-5, atol=2e-5)

    def test_matches_flat_layout(self):
        key = jax.random.PRNGKey(5)
        params = make_tree(key)
        grads = jax.tree.map(
            lambda p: jax.random.normal(jax.random.fold_in(key, 7),
                                        p.shape, p.dtype), params)
        out = {}
        for lay in ("flat", "tree"):
            tx = opt.fused_sgd(1e-2, momentum=0.9, dampening=0.1,
                               weight_decay=1e-3, layout=lay)
            state = tx.init(params)
            p, state = jax.jit(tx.step)(grads, state, params)
            p, _ = jax.jit(tx.step)(grads, state, p)
            out[lay] = p
        for a, b in zip(jax.tree.leaves(out["flat"]), jax.tree.leaves(out["tree"])):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32),
                                       rtol=2e-5, atol=2e-6)


class TestTreeLayoutLAMB:
    def test_matches_flat_layout(self):
        key = jax.random.PRNGKey(11)
        params = make_tree(key)
        grads = jax.tree.map(
            lambda p: jax.random.normal(jax.random.fold_in(key, 13),
                                        p.shape, p.dtype), params)
        out = {}
        for lay in ("flat", "tree"):
            tx = opt.fused_lamb(1e-2, weight_decay=0.01, layout=lay)
            state = tx.init(params)
            p, state = jax.jit(tx.step)(grads, state, params)
            p, _ = jax.jit(tx.step)(grads, state, p)
            out[lay] = p
        for a, b in zip(jax.tree.leaves(out["flat"]),
                        jax.tree.leaves(out["tree"])):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32),
                                       rtol=5e-5, atol=5e-6)

    def test_no_adapt_without_wd(self):
        """use_nvlamb=False + wd=0: both layouts skip trust adaptation."""
        key = jax.random.PRNGKey(17)
        params = make_tree(key)
        grads = jax.tree.map(jnp.ones_like, params)
        out = {}
        for lay in ("flat", "tree"):
            tx = opt.fused_lamb(1e-2, weight_decay=0.0, max_grad_norm=None,
                                layout=lay)
            p, _ = jax.jit(tx.step)(grads, tx.init(params), params)
            out[lay] = p
        for a, b in zip(jax.tree.leaves(out["flat"]),
                        jax.tree.leaves(out["tree"])):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32),
                                       rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("maker,kw", [
    (opt.fused_adagrad, dict(weight_decay=1e-3)),
    (opt.fused_novograd, dict(weight_decay=1e-3)),
])
def test_tree_layout_matches_flat(maker, kw):
    key = jax.random.PRNGKey(23)
    params = make_tree(key)
    grads = jax.tree.map(
        lambda p: jax.random.normal(jax.random.fold_in(key, 29),
                                    p.shape, p.dtype), params)
    out = {}
    for lay in ("flat", "tree"):
        tx = maker(1e-2, layout=lay, **kw)
        state = tx.init(params)
        p, state = jax.jit(tx.step)(grads, state, params)
        p, _ = jax.jit(tx.step)(grads, state, p)
        out[lay] = p
    for a, b in zip(jax.tree.leaves(out["flat"]), jax.tree.leaves(out["tree"])):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=5e-5, atol=5e-6)
