"""Contrib subsystems: focal loss, group norm, index_mul, spatial
parallelism, 2:4 sparsity.

Oracle pattern: apex/contrib/test/<feature>/test_*.py (U) — each feature
vs an unfused reference; spatial conv vs the unsharded conv.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from apex_tpu import mesh as mx
from apex_tpu.contrib import (
    apply_masks,
    compute_mask_2to4,
    group_norm_nhwc,
    halo_exchange,
    index_mul_2d,
    init_masks,
    masked_step,
    sigmoid_focal_loss,
    spatial_conv2d,
)
from apex_tpu.optimizers import fused_sgd


def test_focal_loss_reduces_to_bce_at_gamma0():
    logits = jax.random.normal(jax.random.PRNGKey(0), (16,))
    targets = (jax.random.uniform(jax.random.PRNGKey(1), (16,)) > 0.5)
    fl = sigmoid_focal_loss(logits, targets, alpha=-1, gamma=0.0)
    p = jax.nn.sigmoid(logits)
    bce = -(targets * jnp.log(p) + (~targets) * jnp.log1p(-p))
    np.testing.assert_allclose(np.asarray(fl), np.asarray(bce), rtol=1e-5)


def test_focal_loss_downweights_easy():
    easy = sigmoid_focal_loss(jnp.array([8.0]), jnp.array([1.0]), gamma=2.0)
    hard = sigmoid_focal_loss(jnp.array([-8.0]), jnp.array([1.0]), gamma=2.0)
    assert float(easy[0]) < 1e-6 < float(hard[0])


def test_group_norm_matches_reference():
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 4, 8))
    w = jax.random.normal(jax.random.PRNGKey(1), (8,)) + 1.0
    b = jax.random.normal(jax.random.PRNGKey(2), (8,))
    y = group_norm_nhwc(x, 2, w, b)
    # reference via per-group normalization
    xg = x.reshape(2, 4, 4, 2, 4)
    mean = xg.mean(axis=(1, 2, 4), keepdims=True)
    var = xg.var(axis=(1, 2, 4), keepdims=True)
    ref = ((xg - mean) / np.sqrt(var + 1e-5)).reshape(2, 4, 4, 8) * w + b
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=2e-5,
                               atol=2e-5)


def test_index_mul_2d():
    in1 = jnp.arange(12.0).reshape(4, 3)
    in2 = jnp.ones((2, 3)) * 2
    idx = jnp.array([3, 1])
    out = index_mul_2d(in1, in2, idx)
    np.testing.assert_allclose(np.asarray(out), np.asarray(in1[idx] * 2))


def test_halo_exchange_and_spatial_conv(devices8):
    mesh = mx.build_mesh(cp=4, devices=devices8[:4])
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 8, 3))
    k = jax.random.normal(jax.random.PRNGKey(1), (3, 3, 3, 5)) * 0.1

    ref = lax.conv_general_dilated(
        x, k, (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))

    spec = P(None, "cp", None, None)
    out = jax.jit(jax.shard_map(
        lambda x, k: spatial_conv2d(x, k, axis="cp"),
        mesh=mesh, in_specs=(spec, P()), out_specs=spec,
        check_vma=False))(x, k)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    # halo rows really come from neighbours
    h = jax.jit(jax.shard_map(
        lambda x: halo_exchange(x, 1, axis="cp"),
        mesh=mesh, in_specs=spec, out_specs=P(None, ("cp",), None, None),
        check_vma=False))(x)
    assert h.shape[1] == 16 + 2 * 4  # each shard grew by 2 rows


def test_sparsity_masks_and_step():
    params = {"w": jax.random.normal(jax.random.PRNGKey(0), (8, 6)),
              "b": jnp.ones((3,))}
    masks = init_masks(params)
    assert masks["b"] is None
    m = np.asarray(masks["w"])
    # exactly 2 of every 4 along dim 0 survive
    grouped = m.reshape(2, 4, 6)
    np.testing.assert_array_equal(grouped.sum(axis=1), 2 * np.ones((2, 6)))
    sp = apply_masks(params, masks)
    assert float(jnp.count_nonzero(sp["w"])) == 24.0

    # largest magnitudes retained
    col = np.asarray(params["w"])[:4, 0]
    kept = np.abs(col)[m[:4, 0]]
    dropped = np.abs(col)[~m[:4, 0]]
    assert kept.min() >= dropped.max()

    opt = fused_sgd(0.1)
    st = opt.init(sp)
    step = masked_step(opt.step, masks)
    new_p, _ = step({"w": jnp.ones((8, 6)), "b": jnp.ones((3,))}, st, sp)
    assert float(jnp.count_nonzero(new_p["w"])) == 24.0
