"""Torch-oracle parity for the norm and xentropy kernels: the reference's
own framework as the correctness reference (SURVEY.md §4 — apex tests
compare against unfused torch ops at higher precision; these do exactly
that, where the rest of the suite uses fp32 jnp references)."""

import jax
import jax.numpy as jnp
import numpy as np
import torch
import torch.nn.functional as F

from apex_tpu.contrib import group_norm_nhwc
from apex_tpu.kernels import layer_norm, rms_norm, softmax_cross_entropy


def test_layer_norm_matches_torch_fwd_bwd():
    N, H = 6, 96
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (N, H))
    g = jax.random.normal(jax.random.fold_in(key, 1), (H,)) * 0.3 + 1.0
    b = jax.random.normal(jax.random.fold_in(key, 2), (H,)) * 0.1

    def loss(x, g, b):
        return jnp.sum(layer_norm(x, g, b, eps=1e-5) ** 2)

    val, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(x, g, b)

    tx = torch.tensor(np.asarray(x), requires_grad=True)
    tg = torch.tensor(np.asarray(g), requires_grad=True)
    tb = torch.tensor(np.asarray(b), requires_grad=True)
    ty = F.layer_norm(tx, (H,), tg, tb, eps=1e-5)
    tl = (ty ** 2).sum()
    tl.backward()
    np.testing.assert_allclose(float(val), tl.detach().item(), rtol=1e-5)
    for jg, tgr in zip(grads, (tx.grad, tg.grad, tb.grad)):
        np.testing.assert_allclose(np.asarray(jg), tgr.numpy(),
                                   rtol=2e-4, atol=2e-4)


def test_rms_norm_matches_torch():
    N, H = 4, 64
    x = jax.random.normal(jax.random.PRNGKey(3), (N, H))
    w = jax.random.normal(jax.random.PRNGKey(4), (H,)) * 0.2 + 1.0
    y = rms_norm(x, w, eps=1e-6)
    ty = F.rms_norm(torch.tensor(np.asarray(x)), (H,),
                    torch.tensor(np.asarray(w)), eps=1e-6)
    np.testing.assert_allclose(np.asarray(y), ty.numpy(), rtol=1e-5,
                               atol=1e-5)


def test_group_norm_nhwc_matches_torch():
    N, H, W, C, G = 2, 4, 4, 16, 4
    x = jax.random.normal(jax.random.PRNGKey(5), (N, H, W, C))
    g = jax.random.normal(jax.random.PRNGKey(6), (C,)) * 0.3 + 1.0
    b = jax.random.normal(jax.random.PRNGKey(7), (C,)) * 0.1
    y = group_norm_nhwc(x, G, g, b, eps=1e-5)
    # torch GroupNorm is NCHW
    ty = F.group_norm(
        torch.tensor(np.asarray(x)).permute(0, 3, 1, 2), G,
        torch.tensor(np.asarray(g)), torch.tensor(np.asarray(b)), eps=1e-5
    ).permute(0, 2, 3, 1)
    np.testing.assert_allclose(np.asarray(y), ty.numpy(), rtol=2e-5,
                               atol=2e-5)


def test_softmax_cross_entropy_matches_torch():
    N, V = 12, 37
    logits = jax.random.normal(jax.random.PRNGKey(8), (N, V)) * 3.0
    tgt = jax.random.randint(jax.random.PRNGKey(9), (N,), 0, V)
    tgt = tgt.at[3].set(-100)  # ignore_index row

    for smoothing in (0.0, 0.1):
        loss = softmax_cross_entropy(logits, tgt, label_smoothing=smoothing)
        tl = F.cross_entropy(
            torch.tensor(np.asarray(logits)),
            torch.tensor(np.asarray(tgt), dtype=torch.long),
            label_smoothing=smoothing, ignore_index=-100, reduction="none")
        np.testing.assert_allclose(np.asarray(loss), tl.numpy(),
                                   rtol=2e-5, atol=2e-5)
