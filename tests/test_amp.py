"""amp tests — parity model: apex tests/L0/run_amp/* (U).

Covers policy casting per opt level (test_basic_casts.py analogue), dynamic
scaler growth/backoff/hysteresis, jit-safe overflow skip, and scaler
checkpoint round-trip (test_checkpointing.py analogue).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp


def tree_dtypes(tree):
    return [jnp.asarray(x).dtype for x in jax.tree.leaves(tree)]


class TestPolicy:
    def test_opt_levels(self):
        o0 = amp.get_policy("O0")
        o1 = amp.get_policy("O1")
        o2 = amp.get_policy("O2")
        o3 = amp.get_policy("O3")
        assert o0.compute_dtype == jnp.float32 and o0.loss_scale is None
        assert o1.compute_dtype == jnp.bfloat16 and o1.param_dtype == jnp.float32
        assert o2.param_dtype == jnp.bfloat16 and o2.master_weights
        assert o3.keep_norms_fp32 is False

    def test_fp16_enables_dynamic_scaling(self):
        for lvl in ("O1", "O2", "O3"):
            assert amp.get_policy(lvl, jnp.float16).loss_scale == "dynamic"
            assert amp.get_policy(lvl, jnp.bfloat16).loss_scale is None

    def test_cast_preserves_integers(self):
        p = amp.get_policy("O1")
        tree = {"w": jnp.ones((2, 2)), "step": jnp.int32(3), "mask": jnp.array([True])}
        out = p.cast_to_compute(tree)
        assert out["w"].dtype == jnp.bfloat16
        assert out["step"].dtype == jnp.int32
        assert out["mask"].dtype == jnp.bool_

    def test_unknown_level_raises(self):
        with pytest.raises(ValueError):
            amp.get_policy("O4")
        with pytest.raises(ValueError):
            amp.get_policy("O1", jnp.float64)


class TestScaler:
    def cfg(self, **kw):
        kw.setdefault("init_scale", 8.0)
        kw.setdefault("growth_interval", 3)
        return amp.ScalerConfig(**kw)

    def test_growth_after_interval(self):
        cfg = self.cfg()
        st = cfg.init()
        for _ in range(2):
            st = amp.update(cfg, st, True)
            assert float(st.loss_scale) == 8.0
        st = amp.update(cfg, st, True)  # 3rd clean step → grow
        assert float(st.loss_scale) == 16.0
        assert int(st.growth_count) == 0

    def test_backoff_on_overflow_and_counter_reset(self):
        cfg = self.cfg()
        st = cfg.init()
        st = amp.update(cfg, st, True)
        st = amp.update(cfg, st, False)
        assert float(st.loss_scale) == 4.0
        assert int(st.growth_count) == 0

    def test_hysteresis_delays_backoff(self):
        cfg = self.cfg(hysteresis=2)
        st = cfg.init()
        st = amp.update(cfg, st, False)
        assert float(st.loss_scale) == 8.0  # first overflow tolerated
        st = amp.update(cfg, st, False)
        assert float(st.loss_scale) == 4.0  # second backs off
        st = amp.update(cfg, st, True)
        assert int(st.hysteresis_left) == 2  # clean step restores tolerance

    def test_min_max_clamp(self):
        cfg = self.cfg(init_scale=1.0, min_scale=1.0)
        st = cfg.init()
        st = amp.update(cfg, st, False)
        assert float(st.loss_scale) == 1.0
        cfg = self.cfg(init_scale=2.0 ** 24, max_scale=2.0 ** 24, growth_interval=1)
        st = cfg.init()
        st = amp.update(cfg, st, True)
        assert float(st.loss_scale) == 2.0 ** 24

    def test_update_is_jittable(self):
        cfg = self.cfg()
        upd = jax.jit(lambda s, f: amp.update(cfg, s, f))
        st = upd(cfg.init(), jnp.bool_(False))
        assert float(st.loss_scale) == 4.0

    def test_all_finite(self):
        good = {"a": jnp.ones(3), "i": jnp.arange(3)}
        assert bool(amp.all_finite(good))
        bad = {"a": jnp.array([1.0, jnp.inf]), "b": jnp.ones(2)}
        assert not bool(amp.all_finite(bad))
        nan = {"a": jnp.array([jnp.nan])}
        assert not bool(amp.all_finite(nan))

    def test_state_dict_roundtrip(self):
        cfg = self.cfg()
        st = amp.update(cfg, cfg.init(), False)
        d = amp.Amp.state_dict(st)
        st2 = amp.Amp.load_state_dict(d)
        assert float(st2.loss_scale) == float(st.loss_scale)
        assert int(st2.growth_count) == int(st.growth_count)


class TestScaledGrad:
    def test_grads_unscaled_and_finite_flag(self):
        ctx, _ = amp.initialize(opt_level="O1", half_dtype=jnp.float16)
        st = ctx.init_scaler_state()
        assert float(st.loss_scale) == 2.0 ** 16

        def loss_fn(w):
            return jnp.sum(w ** 2)

        w = jnp.array([1.0, 2.0])
        value, grads, finite = jax.jit(
            lambda w, s: ctx.value_and_grad(loss_fn)(w, scaler_state=s)
        )(w, st)
        np.testing.assert_allclose(np.asarray(grads), [2.0, 4.0], rtol=1e-6)
        np.testing.assert_allclose(float(value), 5.0, rtol=1e-6)
        assert bool(finite)

    def test_overflow_detected_and_step_skipped(self):
        ctx, _ = amp.initialize(opt_level="O1", half_dtype=jnp.float16)
        st = ctx.init_scaler_state()

        def bad_loss(w):
            return jnp.sum(w * jnp.float32(jnp.inf))

        w = jnp.array([1.0])
        _, grads, finite = ctx.value_and_grad(bad_loss)(w, scaler_state=st)
        assert not bool(finite)
        new_w = amp.apply_if_finite(w - 123.0, w, finite)
        np.testing.assert_allclose(np.asarray(new_w), np.asarray(w))
        st2 = ctx.update_scaler(st, finite)
        assert float(st2.loss_scale) == 2.0 ** 15

    def test_multiple_losses_independent_scalers(self):
        """apex's num_losses/loss_id pattern (run_amp
        test_multiple_models_optimizers_losses (U)): each loss carries
        its own scaler state — one overflowing loss backs only its own
        scale off while the healthy loss's scaler grows on schedule."""
        ctx, _ = amp.initialize(opt_level="O1", half_dtype=jnp.float16)
        st_a = ctx.init_scaler_state()
        st_b = ctx.init_scaler_state()
        w = jnp.array([1.0, 2.0])

        def loss_a(w):
            return jnp.sum(w ** 2)

        def loss_b(w):
            return jnp.sum(w * jnp.float32(jnp.inf))

        _, g_a, fin_a = ctx.value_and_grad(loss_a)(w, scaler_state=st_a)
        _, g_b, fin_b = ctx.value_and_grad(loss_b)(w, scaler_state=st_b)
        assert bool(fin_a) and not bool(fin_b)
        # per-loss update keeps the scalers independent
        st_a = ctx.update_scaler(st_a, fin_a)
        st_b = ctx.update_scaler(st_b, fin_b)
        assert float(st_a.loss_scale) == 2.0 ** 16  # clean: unchanged
        assert float(st_b.loss_scale) == 2.0 ** 15  # overflow: backed off
        # the combined step applies only the finite loss's grads
        combined = jax.tree.map(
            lambda ga, gb: ga + amp.apply_if_finite(gb, jnp.zeros_like(gb),
                                                    fin_b), g_a, g_b)
        np.testing.assert_allclose(np.asarray(combined), [2.0, 4.0],
                                   rtol=1e-6)

    def test_has_aux(self):
        ctx, _ = amp.initialize(opt_level="O1", half_dtype=jnp.float16)
        st = ctx.init_scaler_state()

        def loss_fn(w):
            return jnp.sum(w), {"n": w.shape[0]}

        (value, aux), grads, finite = ctx.value_and_grad(loss_fn, has_aux=True)(
            jnp.ones(4), scaler_state=st
        )
        assert aux["n"] == 4 and bool(finite)
        np.testing.assert_allclose(np.asarray(grads), np.ones(4))

    def test_static_scale_never_moves(self):
        ctx, _ = amp.initialize(opt_level="O1", half_dtype=jnp.float16, loss_scale=128.0)
        st = ctx.init_scaler_state()
        st = ctx.update_scaler(st, False)
        assert float(st.loss_scale) == 128.0
        st = ctx.update_scaler(st, True)
        assert float(st.loss_scale) == 128.0

    def test_fp16_loss_scaled_in_fp32(self):
        """Scale 2^16 > float16 max: scaling must happen in fp32 (O3 path)."""
        ctx, _ = amp.initialize(opt_level="O3", half_dtype=jnp.float16)
        st = ctx.init_scaler_state()
        scaled = amp.scale_loss(jnp.float16(2.0), st)
        assert np.isfinite(float(scaled))
        np.testing.assert_allclose(float(scaled), 2.0 * 2.0 ** 16)

    def test_fp16_grads_unscaled_to_fp32(self):
        """Unscale writes fp32 master grads — small components survive."""
        st = amp.ScalerConfig(init_scale=2.0 ** 16).init()
        tiny = jnp.float16(0.5)  # scaled grad; unscaled value 0.5/65536 ≈ 7.6e-6
        out = amp.unscale({"g": tiny}, st)["g"]
        assert out.dtype == jnp.float32
        np.testing.assert_allclose(float(out), 0.5 / 2.0 ** 16, rtol=1e-6)

    def test_bf16_policy_scaler_disabled(self):
        ctx, _ = amp.initialize(opt_level="O1")
        st = ctx.init_scaler_state()
        assert float(st.loss_scale) == 1.0
        st = ctx.update_scaler(st, False)
        assert float(st.loss_scale) == 1.0


class TestEndToEnd:
    def test_fp16_training_converges_with_dynamic_scaling(self):
        """L1-style: tiny regression trained under O1-fp16; loss decreases and
        scaler survives (apex tests/L1 cross-product pattern, minimal)."""
        ctx, apply_fn = amp.initialize(
            lambda w, x: x @ w, opt_level="O1", half_dtype=jnp.float16
        )
        key = jax.random.PRNGKey(0)
        x = jax.random.normal(key, (64, 8))
        true_w = jnp.arange(8.0).reshape(8, 1)
        y = x @ true_w
        w = jnp.zeros((8, 1))

        def loss_fn(w, x, y):
            pred = apply_fn(w, x)
            return jnp.mean((pred - y) ** 2)

        st = ctx.init_scaler_state()

        @jax.jit
        def step(w, st, x, y):
            value, grads, finite = ctx.value_and_grad(loss_fn)(w, x, y, scaler_state=st)
            new_w = amp.apply_if_finite(w - 0.01 * grads, w, finite)
            return value, new_w, ctx.update_scaler(st, finite)

        first = None
        for _ in range(200):
            value, w, st = step(w, st, x, y)
            if first is None:
                first = float(value)
        assert float(value) < first * 0.05
        assert np.isfinite(float(st.loss_scale))


def test_update_scale_hysteresis_call_shape():
    """csrc/update_scale_hysteresis.cu (U) parity: the tracker only
    decrements on overflow, backs off on EVERY overflow once exhausted
    (no refill), growth is fp32-finite-guarded."""
    from apex_tpu.amp import update_scale_hysteresis

    # overflow with budget: spend one, scale unchanged
    s, g, h = update_scale_hysteresis(1024.0, 5, 2, 1)
    assert float(s) == 1024.0 and int(h) == 1 and int(g) == 0
    # budget exhausted: back off; tracker keeps decrementing, no refill
    s, g, h = update_scale_hysteresis(s, g, h, 1)
    assert float(s) == 512.0 and int(h) == 0
    # sustained overflow: backs off again immediately (reference kernel
    # semantics — apex_tpu's own ScalerState policy refills instead)
    s, g, h = update_scale_hysteresis(s, g, h, 1)
    assert float(s) == 256.0 and int(h) == -1
    # clean step at the growth interval: double and reset the counter
    s, g, h = update_scale_hysteresis(512.0, 1999, 2, 0)
    assert float(s) == 1024.0 and int(g) == 0
    # growth that would overflow fp32 is skipped, counter still resets
    s, g, h = update_scale_hysteresis(3e38, 1999, 2, 0)
    assert np.isfinite(float(s)) and float(s) == np.float32(3e38) \
        and int(g) == 0
