"""MoE / expert parallelism tests (beyond-parity component; SURVEY.md §2.5
marks EP absent in apex). Oracle pattern per SURVEY §4: the ep-sharded
layer must match its dense single-device equivalent."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import mesh as mx
from apex_tpu.transformer import moe


def _cfg(**kw):
    base = dict(num_experts=8, hidden_size=16, ffn_hidden_size=32,
                top_k=2, capacity_factor=8.0,  # no drops unless shrunk
                param_dtype=jnp.float32, compute_dtype=jnp.float32)
    base.update(kw)
    return moe.MoEConfig(**base)


def test_moe_ep_matches_dense(devices8):
    """8-way expert parallelism == dense MoE on the same params/tokens."""
    cfg = _cfg(axis="ep")
    params = moe.init_moe(cfg, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (128, cfg.hidden_size))

    dense_cfg = _cfg(axis=None)
    y_dense, aux_dense = moe.moe_ffn(dense_cfg, params, x)

    mesh = mx.build_mesh(ep=8, devices=devices8)
    pspec = moe.moe_pspecs(P)

    def shard_fn(p, xs):
        y, aux = moe.moe_ffn(cfg, p, xs)
        return y, jax.lax.pmean(aux, "ep")

    y_ep, aux_ep = jax.jit(jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(pspec, P("ep")),
        out_specs=(P("ep"), P()),
        check_vma=False))(params, x)

    # Tokens shard over ep (16/rank): per-rank capacity totals the dense
    # budget and capacity_factor=8 means nothing drops, so outputs match.
    np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_dense),
                               rtol=1e-5, atol=1e-5)
    # aux is over local tokens per rank; pmean == dense value only up to
    # which tokens landed where, so compare loosely.
    assert np.isfinite(float(aux_ep)) and float(aux_ep) > 0
    assert abs(float(aux_ep) - float(aux_dense)) < 0.5


def test_moe_capacity_drops_tokens():
    """Capacity 1 with every token routed to one expert: exactly C slots
    survive per slot-priority order, the rest contribute zero."""
    cfg = _cfg(axis=None, top_k=1, num_experts=2, capacity_factor=0.125)
    params = moe.init_moe(cfg, jax.random.PRNGKey(0))
    # Force all tokens to expert 0 with a huge router column.
    k = params["router"]["kernel"]
    params["router"]["kernel"] = k.at[:, 0].set(0.0).at[0, 0].set(100.0)
    x = jnp.zeros((16, cfg.hidden_size)).at[:, 0].set(1.0)
    y, _ = moe.moe_ffn(cfg, params, x)
    # C = ceil(1 * 16 * 0.125 / 2) = 1: only the first token is served
    expert_out = np.asarray(y)
    assert np.any(expert_out[0] != 0)
    np.testing.assert_array_equal(expert_out[1:], 0)


def test_moe_aux_loss_prefers_balance():
    """Switch aux loss: uniform routing scores ~1, collapsed routing ~E."""
    cfg = _cfg(axis=None, top_k=1)
    params = moe.init_moe(cfg, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (256, cfg.hidden_size))

    uniform = dict(params)
    uniform["router"] = {"kernel": jnp.zeros_like(params["router"]["kernel"])}
    _, aux_u = moe.moe_ffn(cfg, uniform, x)

    collapsed = dict(params)
    collapsed["router"] = {"kernel": jnp.zeros_like(
        params["router"]["kernel"]).at[0, 3].set(50.0)}
    x_pos = x.at[:, 0].set(jnp.abs(x[:, 0]) + 0.1)  # logit_3 = 50*x0 > 0
    _, aux_c = moe.moe_ffn(cfg, collapsed, x_pos)

    # uniform: E * sum(1/E * 1/E * E) = 1 (up to top-1 tie-breaking);
    # collapsed: f=P=onehot -> E.
    assert float(aux_c) > 0.9 * cfg.num_experts
    assert float(aux_u) < 1.5


def test_moe_grads_flow_and_are_finite():
    cfg = _cfg(axis=None)
    params = moe.init_moe(cfg, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (64, cfg.hidden_size))

    def loss(p):
        y, aux = moe.moe_ffn(cfg, p, x)
        return jnp.mean(y ** 2) + cfg.aux_loss_coef * aux

    g = jax.grad(loss)(params)
    for path, leaf in jax.tree_util.tree_leaves_with_path(g):
        assert np.all(np.isfinite(np.asarray(leaf))), path
    # router must receive gradient through both gates and aux loss
    assert float(jnp.abs(g["router"]["kernel"]).sum()) > 0
    assert float(jnp.abs(g["experts"]["w1"]).sum()) > 0


def test_moe_shard_mismatch_raises(devices8):
    cfg = _cfg(axis="ep", num_experts=4)  # 4 experts on 8 ranks: invalid
    params = moe.init_moe(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(ep=8, devices=devices8)
    x = jax.random.normal(jax.random.PRNGKey(1), (64, cfg.hidden_size))
    with pytest.raises(ValueError,
                       match="experts shard|not evenly divisible"):
        jax.jit(jax.shard_map(
            lambda p, xs: moe.moe_ffn(cfg, p, xs)[0], mesh=mesh,
            in_specs=(moe.moe_pspecs(P), P("ep")), out_specs=P("ep"),
            check_vma=False))(params, x)


def test_moe_gather_dispatch_matches_einsum(devices8):
    """The linear gather/scatter dispatch and the GShard one-hot einsum
    dispatch are the same permutation — outputs and grads must match."""
    pe = _cfg(axis=None, dispatch="einsum", capacity_factor=1.0)
    pg = _cfg(axis=None, dispatch="gather", capacity_factor=1.0)
    params = moe.init_moe(pe, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (96, pe.hidden_size))

    def loss(cfg_, p):
        y, aux = moe.moe_ffn(cfg_, p, x)
        return jnp.sum(y ** 2) + aux, y

    (le, ye), ge = jax.value_and_grad(
        lambda p: loss(pe, p), has_aux=True)(params)
    (lg, yg), gg = jax.value_and_grad(
        lambda p: loss(pg, p), has_aux=True)(params)
    np.testing.assert_allclose(np.asarray(ye), np.asarray(yg),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(le), float(lg), rtol=1e-6)
    for (path, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(ge),
            jax.tree_util.tree_leaves_with_path(gg)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6, err_msg=str(path))


def test_moe_gather_dispatch_ep_matches_dense(devices8):
    """EP all_to_all on top of the gather dispatch (the at-scale path)."""
    cfg = _cfg(axis="ep", dispatch="gather")
    params = moe.init_moe(cfg, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (128, cfg.hidden_size))
    y_dense, _ = moe.moe_ffn(_cfg(axis=None, dispatch="gather"), params, x)
    mesh = mx.build_mesh(ep=8, devices=devices8)
    y_ep = jax.jit(jax.shard_map(
        lambda p, xs: moe.moe_ffn(cfg, p, xs)[0], mesh=mesh,
        in_specs=(moe.moe_pspecs(P), P("ep")),
        out_specs=P("ep"), check_vma=False))(params, x)
    np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_dense),
                               rtol=1e-5, atol=1e-5)


def test_moe_ep_capacity_is_per_source_rank(devices8):
    """Documented drop semantics under ep: capacity caps each *source
    rank's* slots. All 16 tokens/rank routed to expert 0 with C=2 →
    every rank serves exactly its first 2 tokens, drops the rest."""
    cfg = _cfg(axis="ep", top_k=1, capacity_factor=1.0)  # C = 16/8 = 2
    params = moe.init_moe(cfg, jax.random.PRNGKey(0))
    k = params["router"]["kernel"]
    params["router"]["kernel"] = jnp.zeros_like(k).at[0, 0].set(100.0)
    x = jnp.ones((128, cfg.hidden_size))  # logit_0 = 100 > 0 everywhere

    mesh = mx.build_mesh(ep=8, devices=devices8)
    y = jax.jit(jax.shard_map(
        lambda p, xs: moe.moe_ffn(cfg, p, xs)[0], mesh=mesh,
        in_specs=(moe.moe_pspecs(P), P("ep")), out_specs=P("ep"),
        check_vma=False))(params, x)
    y = np.asarray(y).reshape(8, 16, cfg.hidden_size)  # [rank, token, h]
    served = np.any(y != 0, axis=-1)
    np.testing.assert_array_equal(served[:, :2], True)
    np.testing.assert_array_equal(served[:, 2:], False)
