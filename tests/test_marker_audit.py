"""Unit pins for the tier-1 marker audit (tests/conftest.py): any test
over the wall-clock budget without the ``slow`` marker fails with an
actionable message, keeping the tier-1 budget honest as suites grow.
The predicate is tested directly; the report-mutation hook is exercised
implicitly by every tier-1 run (each passing test flows through it)."""

from conftest import TIER1_BUDGET_S, audit_overtime


def test_audit_predicate_arms():
    # unmarked + over budget = offender
    assert audit_overtime(61.0, False, budget_s=60.0)
    # slow-marked tests are exempt at any duration
    assert not audit_overtime(10_000.0, True, budget_s=60.0)
    # under budget passes unmarked
    assert not audit_overtime(59.9, False, budget_s=60.0)
    # budget <= 0 disables the audit entirely
    assert not audit_overtime(10_000.0, False, budget_s=0.0)
    assert not audit_overtime(10_000.0, False, budget_s=-1.0)


def test_audit_default_budget_sane():
    """The default budget is either 0 (cold compile cache — per-test
    wall time would be compile-dominated, the audit auto-disarms) or
    within the same order as the documented ~60 s CPU-mesh bound — a
    silent bump to hours would defeat the audit."""
    assert TIER1_BUDGET_S == 0.0 or 0 < TIER1_BUDGET_S <= 300.0
