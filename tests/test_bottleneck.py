"""Fused bottleneck block: shapes, residual identity, spatial-parallel
equivalence (the reference's regression oracle: SpatialBottleneck output
must equal Bottleneck output sliced per rank — apex/contrib/test/
bottleneck (U) pattern)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import mesh as mx
from apex_tpu.contrib import bottleneck, init_bottleneck


def test_shapes_and_downsample():
    p = init_bottleneck(jax.random.PRNGKey(0), 64, 32, stride=2)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 16, 64))
    y = bottleneck(p, x, stride=2)
    assert y.shape == (2, 8, 8, 128)
    assert float(y.min()) >= 0.0  # final relu


def test_identity_residual():
    # zero conv3 scale → block output = relu(residual)
    p = init_bottleneck(jax.random.PRNGKey(0), 128, 32)
    p["conv3"]["scale"] = jnp.zeros_like(p["conv3"]["scale"])
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 8, 128))
    np.testing.assert_allclose(
        np.asarray(bottleneck(p, x)), np.asarray(jnp.maximum(x, 0)),
        rtol=1e-6, atol=1e-6)


def test_spatial_parallel_matches_unsharded():
    p = init_bottleneck(jax.random.PRNGKey(0), 32, 16)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 8, 32))
    want = bottleneck(p, x)

    mesh = mx.build_mesh(tp=1, cp=8, devices=jax.devices()[:8])
    got = jax.jit(jax.shard_map(
        lambda xl: bottleneck(p, xl, spatial_axis="cp"),
        mesh=mesh, in_specs=(P(None, "cp"),), out_specs=P(None, "cp"),
        check_vma=False))(x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
