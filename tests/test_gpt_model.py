"""Flagship GPT model: TP/SP parity + end-to-end train step.

Oracle pattern (SURVEY.md §4): the sharded model must match the unsharded
(tp=1) reference bit-for-tolerance at fp32 — the analogue of apex's
tests/L0/run_transformer/test_layers.py comparing parallel layers against
the monolithic nn.Linear (U).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import mesh as mx
from apex_tpu.amp import ScalerConfig
from apex_tpu.models import gpt, training
from apex_tpu.optimizers import fused_adam, fused_sgd

CFG = dict(vocab_size=96, hidden_size=64, num_layers=2, num_heads=4,
           seq_len=32, compute_dtype=jnp.float32)


def _data(key, batch=8, seq=32, vocab=96):
    tok = jax.random.randint(key, (batch, seq), 0, vocab)
    return tok, jnp.roll(tok, -1, axis=1)


def _run(devices, tp, sp, steps=2, remat=True, opt=None, **cfg_kw):
    # parity runs use SGD: it is linear in the gradient, so cross-mesh
    # reduction-order fp noise stays O(eps) instead of being amplified by
    # Adam's zero-moment first step (~lr * sign(g))
    cfg = gpt.GPTConfig(sequence_parallel=sp, remat=remat, **{**CFG, **cfg_kw})
    mesh = mx.build_mesh(tp=tp, devices=devices)
    init_fn, step_fn = training.make_train_step(
        cfg, mesh, opt or fused_sgd(0.1), ScalerConfig(enabled=False))
    state = init_fn(jax.random.PRNGKey(0))
    tok, tgt = _data(jax.random.PRNGKey(1))
    losses = []
    for _ in range(steps):
        state, m = step_fn(state, tok, tgt)
        losses.append(float(m["loss"]))
    return jax.device_get(state.params), losses


@pytest.mark.parametrize("sp", [False, True])
def test_tp_matches_unsharded_reference(devices8, sp):
    ref_params, ref_losses = _run(devices8, tp=1, sp=False)
    tp_params, tp_losses = _run(devices8, tp=4, sp=sp)
    np.testing.assert_allclose(ref_losses, tp_losses, rtol=2e-4)
    flat_r, _ = jax.tree.flatten(ref_params)
    flat_t, _ = jax.tree.flatten(tp_params)
    for r, t in zip(flat_r, flat_t):
        np.testing.assert_allclose(np.asarray(r), np.asarray(t),
                                   rtol=5e-4, atol=5e-5)


def test_loss_decreases(devices8):
    _, losses = _run(devices8, tp=2, sp=True, steps=6, opt=fused_adam(1e-2))
    assert losses[-1] < losses[0]


def test_fp16_dynamic_scaling_path(devices8):
    """fp16 policy: dynamic scaler engages and steps stay finite."""
    cfg = gpt.GPTConfig(sequence_parallel=False, remat=False,
                        **{**CFG, "compute_dtype": jnp.float16})
    mesh = mx.build_mesh(tp=2, devices=devices8)
    init_fn, step_fn = training.make_train_step(
        cfg, mesh, fused_adam(1e-3), ScalerConfig(init_scale=2.0 ** 8))
    state = init_fn(jax.random.PRNGKey(0))
    tok, tgt = _data(jax.random.PRNGKey(1))
    for _ in range(3):
        state, m = step_fn(state, tok, tgt)
        assert np.isfinite(float(m["loss"]))
    assert float(state.scaler.loss_scale) == 2.0 ** 8  # no overflow backoff


def test_remat_matches_no_remat(devices8):
    p1, l1 = _run(devices8, tp=2, sp=False, remat=True)
    p2, l2 = _run(devices8, tp=2, sp=False, remat=False)
    np.testing.assert_allclose(l1, l2, rtol=1e-5)


def test_param_count():
    cfg = gpt.GPTConfig()  # GPT-2 355M-class
    n = cfg.param_count()
    assert 3.0e8 < n < 4.2e8


def test_perf_knobs_match_defaults(devices8):
    """The measured-fast configuration (XLA-fused LN, unrolled layer scan,
    compute-dtype scores) is numerically the same model as the defaults —
    at fp32 compute the score-dtype knob only moves where the softmax
    scale is applied and LN/unroll only reorder fp ops."""
    _, ref = _run(devices8, tp=2, sp=False, steps=1)
    _, fast = _run(devices8, tp=2, sp=False, steps=1, ln_impl="xla",
                   scan_unroll=True, attn_score_dtype="compute")
    np.testing.assert_allclose(ref, fast, rtol=2e-5)


@pytest.mark.parametrize(
    "policy", ["dots", "qkv_fc1", "fc1", "qkv_fc1_attn", "fc1_attn"])
def test_remat_policies_match_full_remat(devices8, policy):
    """Selective-recompute policies change only what is saved, never the
    math."""
    extra = {"attn_impl": "flash"} if policy.endswith("_attn") else {}
    _, ref = _run(devices8, tp=2, sp=False, steps=1, **extra)
    _, sel = _run(devices8, tp=2, sp=False, steps=1, remat_policy=policy,
                  **extra)
    np.testing.assert_allclose(ref, sel, rtol=1e-5)


def test_packed_attn_layout_matches_bhsd(devices8):
    """The lane-packed [b, s, hidden] flash path (hidden a multiple of
    128 → eligible, the production-shape route) is the same model as the
    head-major layout, including under pinned-residual remat — exercises
    the packed custom_vjp and its packed-shape flash_out/flash_lse
    residuals inside the scanned layer stack on the CPU backbone."""
    kw = dict(hidden_size=128, num_heads=2, attn_impl="flash",
              remat_policy="qkv_fc1_attn")
    _, packed = _run(devices8, tp=1, sp=False, steps=2, **kw)
    _, bhsd = _run(devices8, tp=1, sp=False, steps=2,
                   attn_layout="bhsd", **kw)
    np.testing.assert_allclose(packed, bhsd, rtol=1e-5)
    _, full = _run(devices8, tp=1, sp=False, steps=2, hidden_size=128,
                   num_heads=2, attn_impl="flash")
    np.testing.assert_allclose(packed, full, rtol=1e-5)


def test_attn_pinning_requires_flash(devices8):
    with pytest.raises(ValueError, match="flash"):
        _run(devices8, tp=2, sp=False, steps=1, remat_policy="fc1_attn")


def test_attn_residual_pinning_with_flash(devices8):
    """qkv_fc1_attn + the Pallas flash path: pinned (out, lse) kernel
    residuals must reproduce full-remat numerics exactly."""
    _, ref = _run(devices8, tp=2, sp=False, steps=1, attn_impl="flash")
    _, sel = _run(devices8, tp=2, sp=False, steps=1, attn_impl="flash",
                  remat_policy="qkv_fc1_attn")
    np.testing.assert_allclose(ref, sel, rtol=1e-5)


def test_ce_impl_fused_matches_xla(devices8):
    """ce_impl="fused" (Pallas xentropy per chunk, tp=1) equals the
    vocab-parallel XLA CE."""
    _, ref = _run(devices8, tp=1, sp=False, steps=1, ce_chunk=16)
    _, fus = _run(devices8, tp=1, sp=False, steps=1, ce_chunk=16,
                  ce_impl="fused")
    np.testing.assert_allclose(ref, fus, rtol=1e-5)


def test_ce_impl_validated(devices8):
    with pytest.raises(ValueError, match="ce_impl"):
        _run(devices8, tp=1, sp=False, steps=1, ce_impl="nope")


def test_ce_impl_fused_unchunked_matches_xla(devices8):
    _, ref = _run(devices8, tp=1, sp=False, steps=1)
    _, fus = _run(devices8, tp=1, sp=False, steps=1, ce_impl="fused")
    np.testing.assert_allclose(ref, fus, rtol=1e-5)


def test_ce_impl_fused_rejects_sharded_vocab(devices8):
    with pytest.raises(ValueError, match="unsharded"):
        _run(devices8, tp=2, sp=False, steps=1, ce_impl="fused")


# --- clip_grad_norm: global-norm clipping inside the fused step ---------

def _run_clip(devices, tp, clip, *, pp=1, n_micro=1, sp=False, steps=2):
    cfg = gpt.GPTConfig(sequence_parallel=sp, remat=True, **CFG)
    mesh = mx.build_mesh(tp=tp, pp=pp, devices=devices)
    init_fn, step_fn = training.make_train_step(
        cfg, mesh, fused_sgd(0.1), ScalerConfig(enabled=False),
        clip_grad_norm=clip, n_micro=n_micro,
    )
    state = init_fn(jax.random.PRNGKey(0))
    tok, tgt = _data(jax.random.PRNGKey(1))
    losses, norms = [], []
    for _ in range(steps):
        state, m = step_fn(state, tok, tgt)
        losses.append(float(m["loss"]))
        norms.append(float(m["grad_norm"]) if "grad_norm" in m
                     else float("nan"))
    return jax.device_get(state.params), losses, norms


def test_clip_grad_norm_sharded_matches_unsharded(devices8):
    """The model-parallel norm (tp-sharded leaves psum'd, replicated
    leaves counted once) must equal the tp=1 norm, so a *biting* clip
    produces the same trajectory on both meshes."""
    # clip=1e6 never bites (coeff clamps at 1): unclipped trajectory,
    # but the pre-clip norm metric is reported
    _, ref_losses, ref_norms = _run_clip(devices8, tp=1, clip=1e6)
    clip = ref_norms[0] / 2  # bites on every step
    _, l1, n1 = _run_clip(devices8, tp=1, clip=clip)
    _, l4, n4 = _run_clip(devices8, tp=4, clip=clip, sp=True)
    np.testing.assert_allclose(n1, n4, rtol=2e-4)
    np.testing.assert_allclose(l1, l4, rtol=2e-4)
    # clipping changed the trajectory (step 2 sees different params)...
    assert abs(l1[1] - ref_losses[1]) > 1e-6
    # ...but the reported norm is pre-clip, so step 1's matches unclipped
    np.testing.assert_allclose(n1[0], ref_norms[0], rtol=1e-5)


def test_clip_grad_norm_loose_is_identity(devices8):
    ref_params, ref_losses, _ = _run_clip(devices8, tp=2, clip=None)
    par, losses, norms = _run_clip(devices8, tp=2, clip=1e6)
    np.testing.assert_allclose(ref_losses, losses, rtol=1e-6)
    for r, t in zip(jax.tree.leaves(ref_params), jax.tree.leaves(par)):
        np.testing.assert_allclose(np.asarray(r), np.asarray(t), rtol=1e-6)
    assert norms[0] > 0


def test_clip_grad_norm_pipelined(devices8):
    """pp-sharded leaves contribute once per stage: the pp=2 norm equals
    the flat-mesh norm."""
    _, _, ref_norms = _run_clip(devices8, tp=1, clip=1e6)
    _, _, pp_norms = _run_clip(devices8, tp=1, pp=2, n_micro=2, clip=1e6)
    np.testing.assert_allclose(ref_norms[0], pp_norms[0], rtol=2e-4)


def test_clip_grad_norm_overflow_still_skips_step(devices8):
    """An overflowing fp16 step must skip the update even though the
    clip coefficient computed from the nan norm is nan — apply_if_finite
    guards the params, and the next step recovers at the backed-off
    scale."""
    cfg = gpt.GPTConfig(remat=True, **{**CFG,
                                       "compute_dtype": jnp.float16})
    mesh = mx.build_mesh(tp=2, devices=devices8)
    # fp16 max ≈ 65504: an init_scale beyond 2^24 overflows the scaled
    # loss itself, guaranteeing non-finite grads on step one
    init_fn, step_fn = training.make_train_step(
        cfg, mesh, fused_sgd(0.1),
        ScalerConfig(enabled=True, init_scale=2.0 ** 30,
                     max_scale=2.0 ** 30),
        clip_grad_norm=1.0)
    state = init_fn(jax.random.PRNGKey(0))
    params_before = jax.device_get(state.params)
    tok, tgt = _data(jax.random.PRNGKey(1))
    state, m = step_fn(state, tok, tgt)
    assert int(m["grads_finite"]) == 0
    assert float(m["loss_scale"]) == 2.0 ** 29  # backed off
    for r, t in zip(jax.tree.leaves(params_before),
                    jax.tree.leaves(jax.device_get(state.params))):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(t))
    # scale keeps halving until a clean step lands and trains normally
    # (the recovery scale is layout/reduction-order sensitive within a
    # factor of ~2 — the window covers the 2^17 the batch-major layout
    # lands on)
    for _ in range(14):
        state, m = step_fn(state, tok, tgt)
        if int(m["grads_finite"]):
            break
    assert int(m["grads_finite"]) == 1
    assert np.isfinite(float(m["grad_norm"]))


def test_clip_grad_norm_rejects_zero_optimizer(devices8):
    from apex_tpu.optimizers import distributed_fused_adam
    cfg = gpt.GPTConfig(remat=True, **CFG)
    mesh = mx.build_mesh(tp=1, devices=devices8)
    with pytest.raises(ValueError, match="ZeRO"):
        training.make_train_step(
            cfg, mesh, distributed_fused_adam(1e-3),
            ScalerConfig(enabled=False), clip_grad_norm=1.0)
