"""Transducer loss, checkpoint round-trip, RNN cells, weight norm.

Oracles: brute-force numpy DP for RNN-T; save/restore identity for
checkpoints; algebraic identities for weight norm.
"""

import numpy as np

import jax
import jax.numpy as jnp

from apex_tpu.checkpoint import load_checkpoint, save_checkpoint
from apex_tpu.contrib import transducer_joint, transducer_loss
from apex_tpu.reparameterization import (
    apply_weight_norm,
    remove_weight_norm,
    weight_norm_apply,
    weight_norm_init,
)
from apex_tpu.rnn import LSTM, gru_cell


def _ref_rnnt_loss(lp, tgt, T, U, blank=0):
    alpha = np.full((T, U + 1), -np.inf)
    alpha[0, 0] = 0.0
    for t in range(T):
        for u in range(U + 1):
            if t == 0 and u == 0:
                continue
            cands = []
            if t > 0:
                cands.append(alpha[t - 1, u] + lp[t - 1, u, blank])
            if u > 0:
                cands.append(alpha[t, u - 1] + lp[t, u - 1, tgt[u - 1]])
            alpha[t, u] = np.logaddexp.reduce(cands)
    return -(alpha[T - 1, U] + lp[T - 1, U, blank])


def test_transducer_loss_matches_dp_reference():
    rng = np.random.RandomState(0)
    B, T, U, V = 3, 5, 4, 7
    logits = rng.randn(B, T, U + 1, V).astype(np.float32)
    lp = jax.nn.log_softmax(jnp.asarray(logits), axis=-1)
    tgt = rng.randint(1, V, size=(B, U))
    f_len = np.array([5, 4, 3])
    y_len = np.array([4, 2, 3])
    out = transducer_loss(lp, jnp.asarray(tgt), jnp.asarray(f_len),
                          jnp.asarray(y_len))
    for i in range(B):
        ref = _ref_rnnt_loss(np.asarray(lp)[i], tgt[i], f_len[i], y_len[i])
        np.testing.assert_allclose(float(out[i]), ref, rtol=1e-4)


def test_transducer_loss_grads_finite():
    lp = jax.nn.log_softmax(
        jax.random.normal(jax.random.PRNGKey(0), (2, 4, 4, 5)), axis=-1)
    tgt = jnp.ones((2, 3), jnp.int32)
    g = jax.grad(lambda x: jnp.sum(transducer_loss(x, tgt)))(lp)
    assert np.all(np.isfinite(np.asarray(g)))


def test_transducer_joint():
    f = jnp.ones((2, 3, 4))
    g = 2.0 * jnp.ones((2, 5, 4))
    out = transducer_joint(f, g)
    assert out.shape == (2, 3, 5, 4)
    np.testing.assert_allclose(np.asarray(out), 3.0)


def test_checkpoint_roundtrip(tmp_path):
    state = {
        "params": {"w": jnp.arange(6.0).reshape(2, 3)},
        "step": jnp.int32(7),
        "nested": [jnp.ones((4,), jnp.bfloat16)],
    }
    p = save_checkpoint(str(tmp_path / "ckpt"), state, force_npz=True)
    like = jax.tree.map(jnp.zeros_like, state)
    back = load_checkpoint(p, like, force_npz=True)
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(back)):
        assert a.dtype == b.dtype
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32))


def test_lstm_runs_and_matches_manual_step():
    m = LSTM(3, 4)
    p = m.init(jax.random.PRNGKey(0))
    xs = jax.random.normal(jax.random.PRNGKey(1), (5, 2, 3))
    ys, (h, c) = m.apply(p, xs)
    assert ys.shape == (5, 2, 4)
    np.testing.assert_allclose(np.asarray(ys[-1]), np.asarray(h), rtol=1e-6)
    # GRU cell shape sanity
    h2 = gru_cell(xs[0], jnp.zeros((2, 4)),
                  jnp.zeros((3, 12)), jnp.zeros((4, 12)))
    assert h2.shape == (2, 4)


def test_weight_norm_identity():
    w = jax.random.normal(jax.random.PRNGKey(0), (4, 6))
    p = weight_norm_init(w)
    np.testing.assert_allclose(np.asarray(weight_norm_apply(p)),
                               np.asarray(w), rtol=1e-5)
    tree = {"layer": {"kernel": w, "bias": jnp.zeros((6,))}}
    wn = apply_weight_norm(tree)
    assert set(wn["layer"]["kernel"]) == {"g", "v"}
    back = remove_weight_norm(wn)
    np.testing.assert_allclose(np.asarray(back["layer"]["kernel"]),
                               np.asarray(w), rtol=1e-5)


def test_multihead_attn_class_wrappers():
    """SelfMultiheadAttn / EncdecMultiheadAttn at apex's class names wrap
    the functional blocks."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.contrib.multihead_attn import (
        EncdecMultiheadAttn,
        SelfMultiheadAttn,
        encdec_attn,
        self_attn,
    )

    key = jax.random.PRNGKey(0)
    x = jax.random.normal(jax.random.fold_in(key, 1), (8, 2, 32))
    mem = jax.random.normal(jax.random.fold_in(key, 2), (6, 2, 32))

    layer = SelfMultiheadAttn(32, 4, include_norm_add=True)
    p = layer.init(key)
    np.testing.assert_allclose(
        np.asarray(layer(p, x)),
        np.asarray(self_attn(p, x, 4, include_norm_add=True)))

    enc = EncdecMultiheadAttn(32, 4)
    pe = enc.init(key)
    np.testing.assert_allclose(
        np.asarray(enc(pe, x, mem)),
        np.asarray(encdec_attn(pe, x, mem, 4)))


def test_fp16_optimizer_apex_ctor_shapes():
    """FP16_Optimizer accepts apex's constructor shapes."""
    import pytest as _pytest

    from apex_tpu.fp16_utils import FP16_Optimizer
    from apex_tpu.optimizers import fused_sgd

    o1 = FP16_Optimizer(fused_sgd(1e-2), 128.0)  # positional static scale
    assert float(o1.scaler.init_scale) == 128.0
    assert o1.scaler.growth_factor == 1.0
    o2 = FP16_Optimizer(fused_sgd(1e-2), static_loss_scale=64.0)
    assert float(o2.scaler.init_scale) == 64.0
    o3 = FP16_Optimizer(
        fused_sgd(1e-2), dynamic_loss_scale=True,
        dynamic_loss_args={"init_scale": 1024.0, "scale_window": 500})
    assert float(o3.scaler.init_scale) == 1024.0
    assert o3.scaler.growth_interval == 500
    o4 = FP16_Optimizer(fused_sgd(1e-2), dynamic_loss_scale=True)
    assert o4.scaler.growth_interval == 1000  # DynamicLossScaler default


def test_capabilities_registry():
    """Runtime capabilities registry replaces apex's build-time feature
    flags (SURVEY.md §5 'Config / flag system')."""
    import apex_tpu

    caps = apex_tpu.capabilities()
    for always in ("amp", "fused_optimizers", "flash_attention",
                   "transformer", "syncbn", "context_parallel"):
        assert caps[always] is True
    assert caps["backend"] == "cpu"  # conftest forces the CPU platform
    assert caps["pallas_native"] is False  # interpret mode off-TPU
    assert isinstance(caps["native_host_runtime"], bool)
    assert apex_tpu.has_capability("xentropy")
    assert not apex_tpu.has_capability("nonexistent_feature")


def test_capabilities_repeated_access():
    """apex_tpu.capabilities stays the callable on every access (the
    submodule must not shadow the lazily-exported function)."""
    import apex_tpu

    first = apex_tpu.capabilities
    second = apex_tpu.capabilities
    assert callable(first) and callable(second)
    assert apex_tpu.capabilities()["amp"] is True
    assert apex_tpu.capabilities()["amp"] is True  # second call, same result


def test_transformer_layers_ln_wrapper():
    """apex/transformer/layers/layer_norm.py (U): get_layer_norm returns a
    working norm; FastLayerNorm and FusedLayerNorm are the same kernel on
    TPU (SURVEY.md 2.4 'merge with core LN kernel')."""
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.transformer import layers

    assert layers.FastLayerNorm is layers.FusedLayerNorm
    x = jnp.arange(24, dtype=jnp.float32).reshape(2, 12)
    y = layers.get_layer_norm(eps=1e-6, persist_layer_norm=True)(x)
    ref = (x - x.mean(-1, keepdims=True)) / jnp.sqrt(
        x.var(-1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    r = layers.get_layer_norm(rms=True)(x)
    rref = x / jnp.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-5)
    np.testing.assert_allclose(np.asarray(r), np.asarray(rref),
                               rtol=1e-5, atol=1e-5)


def test_transformer_testing_helpers():
    """apex/transformer/testing (U) role: toy configs drive the real model
    stack; device helpers centralise the CPU-simulation backbone."""
    import jax

    from apex_tpu.models import gpt
    from apex_tpu.transformer import testing as ttesting

    cfg = ttesting.standalone_gpt_config(num_layers=1)
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    assert params is not None
    bcfg = ttesting.standalone_bert_config()
    assert bcfg.hidden_size == 64
    assert len(ttesting.assert_devices(8)) == 8
