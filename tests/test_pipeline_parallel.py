"""Pipeline parallelism: schedules, p2p, and full-model parity.

Parity model: apex tests/L0/run_transformer/test_pipeline_parallel_fwd_bwd.py
(U) — losses/grads under PP must equal the no-PP reference — plus
test_p2p_comm.py for the transfer primitives.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from apex_tpu import mesh as mx
from apex_tpu.amp import ScalerConfig
from apex_tpu.models import gpt, training
from apex_tpu.optimizers import fused_sgd
from apex_tpu.transformer import parallel_state as ps
from apex_tpu.transformer.pipeline_parallel import (
    forward_backward_no_pipelining,
    get_forward_backward_func,
    recv_forward,
    send_backward,
    send_forward,
)

CFG = dict(vocab_size=96, hidden_size=64, num_layers=4, num_heads=4,
           seq_len=32, compute_dtype=jnp.float32, remat=False)


def smap(f, mesh, in_specs, out_specs):
    return jax.jit(jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False))


# -- p2p primitives --------------------------------------------------------
def test_p2p_send_forward_backward(devices8):
    mesh = mx.build_mesh(pp=4, devices=devices8[:4])
    x = jnp.arange(4.0)

    def f(x):
        r = lax.axis_index("pp").astype(jnp.float32)
        fwd = send_forward(x + r)
        bwd = send_backward(x + r)
        return fwd, bwd

    fwd, bwd = smap(f, mesh, P("pp"), (P("pp"), P("pp")))(x)
    fwd = np.asarray(fwd).reshape(4, 1)
    bwd = np.asarray(bwd).reshape(4, 1)
    # stage 0 receives zeros; stage i receives stage i-1's value (2*(i-1))
    assert fwd[0, 0] == 0.0
    np.testing.assert_allclose(fwd[1:, 0], 2.0 * np.arange(3))
    # last stage receives zeros from the backward direction
    assert bwd[3, 0] == 0.0
    # recv_forward is the same collective as send_forward (SPMD pairing)
    fwd2 = smap(lambda x: recv_forward(x), mesh, P("pp"), P("pp"))(x)
    np.testing.assert_allclose(np.asarray(fwd2).reshape(4),
                               [0.0, 0.0, 1.0, 2.0])


# -- no-pipelining schedule ------------------------------------------------
def test_no_pipelining_grad_accumulation(devices8):
    w = jnp.array([2.0, -1.0])
    xs = jnp.arange(8.0).reshape(4, 2)  # 4 microbatches

    def loss_fn(w, x):
        return jnp.sum((x @ w) ** 2)

    loss, grads = forward_backward_no_pipelining(loss_fn, w, xs, n_micro=4)
    ref_l, ref_g = jax.value_and_grad(
        lambda w: sum(loss_fn(w, xs[i]) for i in range(4)) / 4.0)(w)
    np.testing.assert_allclose(loss, ref_l, rtol=1e-6)
    np.testing.assert_allclose(grads, ref_g, rtol=1e-6)


def test_schedule_selector(devices8):
    ps.initialize_model_parallel(1, 2, devices=devices8)
    assert get_forward_backward_func().__name__ == (
        "forward_backward_pipelining_without_interleaving")
    ps.initialize_model_parallel(1, 2, 2, devices=devices8)
    assert get_forward_backward_func().__name__ == (
        "forward_backward_pipelining_with_interleaving")
    ps.initialize_model_parallel(2, 1, devices=devices8)
    assert get_forward_backward_func().__name__ == (
        "forward_backward_single_stage")
    ps.destroy_model_parallel()


# -- full-model PP parity --------------------------------------------------
def _ref_grads(cfg, params, tok, tgt, devices):
    mesh1 = mx.build_mesh(tp=1, devices=devices[:1])
    ps1 = gpt.param_specs(cfg)
    g = smap(
        lambda p, t, y: jax.grad(lambda q: gpt.loss(cfg, q, t, y))(p),
        mesh1, (ps1, P(), P()), ps1)(params, tok, tgt)
    return jax.device_get(g)


@pytest.mark.parametrize("pp,vpp,n_micro", [(2, 1, 2), (2, 2, 3), (4, 1, 6)])
def test_pipeline_grads_match_reference(devices8, pp, vpp, n_micro):
    cfg = gpt.GPTConfig(**CFG)
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    tok = jax.random.randint(jax.random.PRNGKey(1), (6, 32), 0, 96)
    tgt = jnp.roll(tok, -1, 1)
    g_ref = _ref_grads(cfg, params, tok, tgt, devices8)

    mesh = mx.build_mesh(tp=1, pp=pp, dp=1, devices=devices8[:pp])
    ps2 = gpt.param_specs(cfg, pipeline=True)
    pp_params = gpt.interleave_layers(params, cfg.num_layers, pp, vpp)

    def gfn(p, t, y):
        g = jax.grad(lambda q: gpt.pipeline_loss(
            cfg, q, t, y, n_micro=n_micro, n_chunks=vpp))(p)
        return {k: (v if k == "layers"
                    else jax.tree.map(lambda x: lax.psum(x, "pp"), v))
                for k, v in g.items()}

    g_pp = jax.device_get(
        smap(gfn, mesh, (ps2, P(), P()), ps2)(pp_params, tok, tgt))
    inv = np.argsort(gpt.interleave_permutation(cfg.num_layers, pp, vpp))
    g_pp = {**g_pp,
            "layers": jax.tree.map(lambda x: np.asarray(x)[inv],
                                   g_pp["layers"])}
    for (path, a), b in zip(jax.tree_util.tree_flatten_with_path(g_ref)[0],
                            jax.tree.leaves(g_pp)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6,
            err_msg=jax.tree_util.keystr(path))


def test_pp_train_step_matches_reference(devices8):
    """3D mesh (pp=2, tp=2, dp=2) + SP + microbatches: losses track the
    single-device run through SGD steps."""
    tok = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 96)
    tgt = jnp.roll(tok, -1, 1)

    def run(tp, pp, sp, n_micro=1, vpp=1):
        cfg = gpt.GPTConfig(sequence_parallel=sp,
                            **{**CFG, "remat": True})
        mesh = mx.build_mesh(tp=tp, pp=pp, devices=devices8)
        i, s = training.make_train_step(
            cfg, mesh, fused_sgd(0.1), ScalerConfig(enabled=False),
            n_micro=n_micro, n_chunks=vpp)
        st = i(jax.random.PRNGKey(0))
        out = []
        for _ in range(3):
            st, m = s(st, tok, tgt)
            out.append(float(m["loss"]))
        return out

    ref = run(1, 1, False)
    np.testing.assert_allclose(run(2, 2, True, n_micro=2), ref, rtol=2e-4)
    np.testing.assert_allclose(run(1, 2, False, n_micro=2, vpp=2), ref,
                               rtol=2e-4)
    # pp=1 grad accumulation path must match too
    np.testing.assert_allclose(run(2, 1, False, n_micro=2), ref, rtol=2e-4)


def test_single_stage_with_aux_matches_flat_forward(devices8):
    """The pp=1 schedule's with_aux branch must agree with the flat
    forward: same CE and same accumulated MoE aux (drop-in contract of
    get_forward_backward_func across topologies)."""
    import jax.numpy as jnp

    from apex_tpu.amp import ScalerConfig
    from apex_tpu.models import training
    from apex_tpu.optimizers import fused_adam
    from apex_tpu.transformer.testing import standalone_gpt_config

    cfg = standalone_gpt_config(num_experts=4, moe_top_k=2,
                                moe_capacity_factor=4.0)
    tok = jax.random.randint(jax.random.PRNGKey(3), (8, 32), 0, 256)
    tgt = jax.random.randint(jax.random.PRNGKey(4), (8, 32), 0, 256)

    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    from jax.sharding import PartitionSpec as P
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    pspecs = gpt.param_specs(cfg)

    flat = jax.jit(jax.shard_map(
        lambda p, t, y: gpt.loss(cfg, p, t, y), mesh=mesh,
        in_specs=(pspecs, P(None, None), P(None, None)),
        out_specs=P(), check_vma=False))(params, tok, tgt)

    def single_stage(p, t, y):
        from apex_tpu.transformer.pipeline_parallel.schedules import (
            forward_backward_single_stage,
        )
        n_micro = 2
        mb = t.shape[0] // n_micro
        toks = t.reshape(n_micro, mb, -1)

        def inject(m):
            tm = jax.lax.dynamic_index_in_dim(toks, m, 0, keepdims=False)
            return gpt._embed(cfg, p, tm)

        def chunk_fn(c, x):
            del c
            return gpt._scan_blocks(cfg, x, p["layers"])

        def loss_of(outs):
            # outs [n_micro, mb, s, h]: microbatches merge contiguously
            h = outs.reshape(t.shape[0], outs.shape[2], cfg.hidden_size)
            h = gpt._layer_norm(cfg, h, p["final_ln"]["scale"],
                                p["final_ln"]["bias"])
            from apex_tpu.transformer.tensor_parallel.mappings import (
                copy_to_tensor_model_parallel_region,
            )
            h = copy_to_tensor_model_parallel_region(h, cfg.axis)
            return gpt._ce_of_hidden(cfg, p, h,
                                     y.reshape(t.shape[0], -1))

        item = jax.ShapeDtypeStruct((mb, 32, cfg.hidden_size),
                                    cfg.compute_dtype)
        ce, aux = forward_backward_single_stage(
            chunk_fn, inject, loss_of, n_micro, item, with_aux=True)
        return ce + jnp.float32(cfg.moe_aux_coef) * aux / n_micro

    got = jax.jit(jax.shard_map(
        single_stage, mesh=mesh,
        in_specs=(pspecs, P(None, None), P(None, None)),
        out_specs=P(), check_vma=False))(params, tok, tgt)
    # microbatched aux is a per-microbatch estimator (nonlinear in the
    # split): CE matches tightly, aux term within its small coef
    np.testing.assert_allclose(float(got), float(flat), rtol=5e-3)
