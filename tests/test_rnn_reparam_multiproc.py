"""Torch-oracle coverage for the RNN cells (the parity claim tested
against the reference implementation itself) and the multiproc shim's
single-host no-op contract. Layer-shape and weight-norm roundtrip
behaviour live in test_misc_parity.py."""

import jax
import jax.numpy as jnp
import numpy as np
import torch

from apex_tpu import rnn


def test_lstm_cell_matches_torch():
    I, H = 6, 8
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 5)
    wi = jax.random.normal(ks[0], (I, 4 * H)) * 0.2
    wh = jax.random.normal(ks[1], (H, 4 * H)) * 0.2
    b = jax.random.normal(ks[2], (4 * H,)) * 0.1
    x = jax.random.normal(ks[3], (3, I))
    h0 = jnp.zeros((3, H)); c0 = jnp.zeros((3, H))
    h1, c1 = rnn.lstm_cell(x, h0, c0, wi, wh, b)

    cell = torch.nn.LSTMCell(I, H)
    with torch.no_grad():
        cell.weight_ih.copy_(torch.tensor(np.asarray(wi).T))
        cell.weight_hh.copy_(torch.tensor(np.asarray(wh).T))
        cell.bias_ih.copy_(torch.tensor(np.asarray(b)))
        cell.bias_hh.zero_()
        th, tc = cell(torch.tensor(np.asarray(x)),
                      (torch.zeros(3, H), torch.zeros(3, H)))
    np.testing.assert_allclose(np.asarray(h1), th.numpy(), rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(c1), tc.numpy(), rtol=1e-5,
                               atol=1e-5)


def test_gru_cell_matches_torch():
    I, H = 5, 7
    key = jax.random.PRNGKey(1)
    ks = jax.random.split(key, 4)
    wi = jax.random.normal(ks[0], (I, 3 * H)) * 0.2
    wh = jax.random.normal(ks[1], (H, 3 * H)) * 0.2
    x = jax.random.normal(ks[2], (2, I))
    h0 = jax.random.normal(ks[3], (2, H)) * 0.1
    h1 = rnn.gru_cell(x, h0, wi, wh)

    cell = torch.nn.GRUCell(I, H, bias=False)
    with torch.no_grad():
        cell.weight_ih.copy_(torch.tensor(np.asarray(wi).T))
        cell.weight_hh.copy_(torch.tensor(np.asarray(wh).T))
        th = cell(torch.tensor(np.asarray(x)), torch.tensor(np.asarray(h0)))
    np.testing.assert_allclose(np.asarray(h1), th.numpy(), rtol=1e-5,
                               atol=1e-5)


def test_multiproc_single_host_noop():
    """No coordinator → no-op (single-controller bring-up); must not touch
    jax.distributed state."""
    from jax._src import distributed as jdist

    from apex_tpu.parallel import initialize_distributed

    before = jdist.global_state.client
    initialize_distributed()  # returns without error, no rendezvous
    assert jdist.global_state.client is before  # untouched
