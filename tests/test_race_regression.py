"""Overlap-equivalence regression tests (SURVEY.md §5 'race detection').

The reference's only race regression is tests/distributed/DDP/
ddp_race_condition_test.py (U): the bucketed allreduce overlapped with
backward must produce the same gradients as one monolithic reduce. XLA has
no data races, but the *scheduling-equivalence* property is still worth
pinning: flat-buffer (bucketed) collectives, per-tensor collectives, and
in-step reductions must agree bitwise; pipelined and non-pipelined
microbatch schedules must agree numerically.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import mesh as mx
from apex_tpu.multi_tensor import pack, unpack
from apex_tpu.parallel.distributed import allreduce_gradients, flat_dist_call


@pytest.fixture
def dp8():
    return mx.build_mesh(tp=1, devices=jax.devices()[:8])


def _grads(key):
    ks = jax.random.split(key, 3)
    return {
        "w": jax.random.normal(ks[0], (64, 32)),
        "b": jax.random.normal(ks[1], (32,)),
        "emb": jax.random.normal(ks[2], (128, 16)),
    }


def test_bucketed_equals_monolithic_bitwise(dp8):
    """apex's race test oracle: flat-bucketed reduce == per-tensor reduce,
    bit-for-bit (same psum, same operand order)."""
    grads = _grads(jax.random.PRNGKey(0))

    def bucketed(g):
        bufs, layout = pack(g)
        reduced = [jax.lax.psum(b, "dp") for b in bufs]
        return unpack(reduced, layout)

    def monolithic(g):
        return jax.tree.map(lambda x: jax.lax.psum(x, "dp"), g)

    spec = jax.tree.map(lambda _: P(), grads)
    run = lambda f: jax.jit(jax.shard_map(
        f, mesh=dp8, in_specs=(spec,), out_specs=spec, check_vma=False))(
            grads)
    a, b = run(bucketed), run(monolithic)
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_allreduce_gradients_matches_flat_dist_call(dp8):
    grads = _grads(jax.random.PRNGKey(1))
    spec = jax.tree.map(lambda _: P(), grads)

    a = jax.jit(jax.shard_map(
        lambda g: allreduce_gradients(g, gradient_average=False),
        mesh=dp8, in_specs=(spec,), out_specs=spec, check_vma=False))(grads)
    b = jax.jit(jax.shard_map(
        lambda g: flat_dist_call(g, op="psum"),
        mesh=dp8, in_specs=(spec,), out_specs=spec, check_vma=False))(grads)
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_pipelined_loss_equals_single_stage():
    """PP schedule equivalence: the 1F1B ring over pp=2 must compute the
    same loss as the same model with no pipeline (the reference's
    test_pipeline_parallel_fwd_bwd.py oracle: 'losses under PP == no-PP
    reference' (U))."""
    from apex_tpu.models import gpt

    cfg = gpt.GPTConfig(vocab_size=64, hidden_size=32, num_layers=4,
                        num_heads=2, seq_len=16, remat=False,
                        compute_dtype=jnp.float32)
    params = jax.jit(lambda k: gpt.init(cfg, k))(jax.random.PRNGKey(0))
    tok = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
    tgt = jnp.roll(tok, -1, 1)

    mesh1 = mx.build_mesh(tp=1, devices=jax.devices()[:1])
    pspec = gpt.param_specs(cfg)
    base = jax.jit(jax.shard_map(
        lambda p: gpt.loss(cfg, p, tok, tgt), mesh=mesh1,
        in_specs=(pspec,), out_specs=P(), check_vma=False))(params)

    mesh = mx.build_mesh(tp=1, pp=2, dp=1, devices=jax.devices()[:2])
    pp_params = gpt.interleave_layers(params, cfg.num_layers, 2)
    pspec_pp = gpt.param_specs(cfg, pipeline=True)
    pp = jax.jit(jax.shard_map(
        lambda p: gpt.pipeline_loss(cfg, p, tok, tgt, n_micro=2),
        mesh=mesh, in_specs=(pspec_pp,), out_specs=P(), check_vma=False))(
            pp_params)
    np.testing.assert_allclose(float(pp), float(base), rtol=1e-5)
