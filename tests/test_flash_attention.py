"""Flash attention kernel tests vs unfused jnp reference.

Parity model: apex/contrib/test/fmha + fast_multihead_attn tests (U) —
fused attention vs straightforward softmax(QK^T)V at fp32.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.kernels.flash_attention import (
    flash_attention,
    flash_attention_bsh,
    mha,
)


def _ref_attention(q, k, v, causal=False, scale=None, kv_lengths=None):
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / d ** 0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * s
    sk = k.shape[2]
    if kv_lengths is not None:
        col = jnp.arange(sk)[None, None, None, :]
        logits = jnp.where(col < kv_lengths[:, None, None, None], logits, -1e30)
    if causal:
        sq = q.shape[2]
        mask = jnp.tril(jnp.ones((sq, sk), bool))
        logits = jnp.where(mask, logits, -1e30)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_flash_forward(dtype, causal):
    b, h, s, d = 2, 3, 24, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, h, s, d)).astype(dtype)
    k = jax.random.normal(ks[1], (b, h, s, d)).astype(dtype)
    v = jax.random.normal(ks[2], (b, h, s, d)).astype(dtype)

    out = flash_attention(q, k, v, causal=causal)
    ref = _ref_attention(q, k, v, causal=causal)
    tol = dict(rtol=3e-2, atol=3e-2) if dtype == jnp.bfloat16 else dict(rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), **tol)


def test_flash_cross_attention_unequal_seq():
    b, h, sq, sk, d = 2, 2, 10, 30, 8
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (b, h, sq, d))
    k = jax.random.normal(ks[1], (b, h, sk, d))
    v = jax.random.normal(ks[2], (b, h, sk, d))
    out = flash_attention(q, k, v)
    ref = _ref_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_flash_kv_lengths():
    b, h, s, d = 3, 2, 16, 8
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q = jax.random.normal(ks[0], (b, h, s, d))
    k = jax.random.normal(ks[1], (b, h, s, d))
    v = jax.random.normal(ks[2], (b, h, s, d))
    lengths = jnp.array([16, 7, 1])
    out = flash_attention(q, k, v, kv_lengths=lengths)
    ref = _ref_attention(q, k, v, kv_lengths=lengths)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients(causal):
    b, h, s, d = 2, 2, 12, 8
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(ks[0], (b, h, s, d))
    k = jax.random.normal(ks[1], (b, h, s, d))
    v = jax.random.normal(ks[2], (b, h, s, d))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_ref_attention(q, k, v, causal=causal) ** 2)

    g = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_, name in zip(g, gref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-4, err_msg=f"d{name}")


def test_flash_gradients_with_lengths():
    b, h, s, d = 2, 2, 16, 8
    ks = jax.random.split(jax.random.PRNGKey(4), 3)
    q = jax.random.normal(ks[0], (b, h, s, d))
    k = jax.random.normal(ks[1], (b, h, s, d))
    v = jax.random.normal(ks[2], (b, h, s, d))
    lengths = jnp.array([11, 5])

    g = jax.grad(lambda q, k, v: jnp.sum(
        flash_attention(q, k, v, kv_lengths=lengths) ** 2), argnums=(0, 1, 2))(q, k, v)
    gref = jax.grad(lambda q, k, v: jnp.sum(
        _ref_attention(q, k, v, kv_lengths=lengths) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b_, name in zip(g, gref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-4, err_msg=f"d{name}")
    # masked-out keys receive zero grad
    assert np.allclose(np.asarray(g[1])[0, :, 11:], 0.0)
    assert np.allclose(np.asarray(g[2])[1, :, 5:], 0.0)


def test_mha_layout_wrapper():
    b, s, h, d = 2, 8, 2, 8
    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    q = jax.random.normal(ks[0], (b, s, h, d))
    k = jax.random.normal(ks[1], (b, s, h, d))
    v = jax.random.normal(ks[2], (b, s, h, d))
    out = mha(q, k, v, causal=True)
    ref = jnp.swapaxes(_ref_attention(
        jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2), jnp.swapaxes(v, 1, 2),
        causal=True), 1, 2)
    assert out.shape == (b, s, h, d)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_long_sequence_multiblock():
    # explicit small tiles force multiple q/k blocks regardless of the
    # (larger) tuned defaults, exercising the online-softmax merge
    b, h, s, d = 1, 1, 300, 8
    ks = jax.random.split(jax.random.PRNGKey(6), 3)
    q = jax.random.normal(ks[0], (b, h, s, d))
    k = jax.random.normal(ks[1], (b, h, s, d))
    v = jax.random.normal(ks[2], (b, h, s, d))
    out = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    ref = _ref_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=5e-5, atol=5e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_fused_vs_split_backward(monkeypatch, causal):
    """The single-sweep fused backward (dQ in full-length VMEM scratch)
    and the two-sweep fallback accumulate in the same block order —
    gradients must agree to float tolerance, and both must match the
    reference. Tiny explicit tiles force multi-block accumulation."""
    b, h, s, d = 2, 2, 20, 8
    ks = jax.random.split(jax.random.PRNGKey(8), 3)
    q = jax.random.normal(ks[0], (b, h, s, d))
    k = jax.random.normal(ks[1], (b, h, s, d))
    v = jax.random.normal(ks[2], (b, h, s, d))
    # composed causal ∧ length masking in one predicate when causal
    lengths = jnp.array([17, 9])

    def grads(mode):
        monkeypatch.setenv("APEX_TPU_FLASH_BWD", mode)
        return jax.grad(lambda q, k, v: jnp.sum(flash_attention(
            q, k, v, causal=causal, kv_lengths=lengths,
            block_q=8, block_k=8) ** 2), argnums=(0, 1, 2))(q, k, v)

    gf, gs = grads("fused"), grads("split")
    gref = jax.grad(lambda q, k, v: jnp.sum(_ref_attention(
        q, k, v, causal=causal, kv_lengths=lengths) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b_, r, name in zip(gf, gs, gref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-6, atol=1e-6, err_msg=f"d{name}")
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=1e-4, atol=1e-4, err_msg=f"d{name}")


def test_misaligned_length_default_tiles():
    """A length just past a tile multiple: _fit_block shrinks the tile
    instead of padding by up to a whole masked-out block; fwd+bwd match
    the reference."""
    b, h, s, d = 1, 2, 1040, 8
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    q = jax.random.normal(ks[0], (b, h, s, d))
    k = jax.random.normal(ks[1], (b, h, s, d))
    v = jax.random.normal(ks[2], (b, h, s, d))

    def f(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

    def ref(q, k, v):
        return jnp.sum(_ref_attention(q, k, v, causal=True) ** 2)

    np.testing.assert_allclose(float(f(q, k, v)), float(ref(q, k, v)),
                               rtol=1e-4)
    g = jax.grad(f)(q, k, v)
    gr = jax.grad(ref)(q, k, v)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr),
                               rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# lane-packed [b, s, hidden] layout
# ---------------------------------------------------------------------------

def _ref_bsh(q, k, v, num_heads, causal=False, kv_lengths=None):
    b, s, hid = q.shape
    d = hid // num_heads
    split = lambda x: jnp.transpose(
        x.reshape(b, x.shape[1], num_heads, d), (0, 2, 1, 3))
    out = _ref_attention(split(q), split(k), split(v), causal=causal,
                         kv_lengths=kv_lengths)
    return jnp.transpose(out, (0, 2, 1, 3)).reshape(b, s, hid)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("heads,d", [(4, 64), (2, 128), (8, 32)])
def test_flash_bsh_forward(heads, d, causal):
    """Packed kernel vs reference across lane-group geometries (G = 2,
    1, 4 sub-heads per 128-lane group)."""
    b, s = 2, 40
    hid = heads * d
    ks = jax.random.split(jax.random.PRNGKey(10), 3)
    q = jax.random.normal(ks[0], (b, s, hid))
    k = jax.random.normal(ks[1], (b, s, hid))
    v = jax.random.normal(ks[2], (b, s, hid))
    out = flash_attention_bsh(q, k, v, num_heads=heads, causal=causal)
    ref = _ref_bsh(q, k, v, heads, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_bsh_kv_lengths_causal_composed():
    b, s, heads, d = 3, 24, 2, 64
    hid = heads * d
    ks = jax.random.split(jax.random.PRNGKey(11), 3)
    q = jax.random.normal(ks[0], (b, s, hid))
    k = jax.random.normal(ks[1], (b, s, hid))
    v = jax.random.normal(ks[2], (b, s, hid))
    lengths = jnp.array([24, 9, 1])
    out = flash_attention_bsh(q, k, v, num_heads=heads, causal=True,
                              kv_lengths=lengths)
    ref = _ref_bsh(q, k, v, heads, causal=True, kv_lengths=lengths)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_bsh_gradients(causal):
    b, s, heads, d = 2, 16, 2, 64
    hid = heads * d
    ks = jax.random.split(jax.random.PRNGKey(12), 3)
    q = jax.random.normal(ks[0], (b, s, hid))
    k = jax.random.normal(ks[1], (b, s, hid))
    v = jax.random.normal(ks[2], (b, s, hid))

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention_bsh(q, k, v, num_heads=heads, causal=causal) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_ref_bsh(q, k, v, heads, causal=causal) ** 2)

    g = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)


def test_flash_bsh_kv_lengths_multigroup_gradients():
    """hidden > 128 (n_grp = 2 lane groups) with per-batch lengths,
    forward AND gradients: exercises the grid-index → batch decomposition
    of the length lookup and the masked packed backward."""
    b, s, heads, d = 3, 20, 4, 64
    hid = heads * d  # 256 → n_grp = 2
    ks = jax.random.split(jax.random.PRNGKey(15), 3)
    q = jax.random.normal(ks[0], (b, s, hid))
    k = jax.random.normal(ks[1], (b, s, hid))
    v = jax.random.normal(ks[2], (b, s, hid))
    lengths = jnp.array([20, 11, 3])

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention_bsh(
            q, k, v, num_heads=heads, causal=True, kv_lengths=lengths) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(
            _ref_bsh(q, k, v, heads, causal=True, kv_lengths=lengths) ** 2)

    np.testing.assert_allclose(
        np.asarray(loss_flash(q, k, v)), np.asarray(loss_ref(q, k, v)),
        rtol=2e-5)
    g = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)


def test_flash_bsh_bwd_env_override(monkeypatch):
    """APEX_TPU_FLASH_BWD=split routes the packed entry point through the
    head-major path (the packed kernels are fused-only); invalid values
    raise — the documented contract holds on the new default path."""
    b, s, heads, d = 2, 16, 2, 64
    ks = jax.random.split(jax.random.PRNGKey(16), 3)
    q = jax.random.normal(ks[0], (b, s, heads * d))
    k = jax.random.normal(ks[1], (b, s, heads * d))
    v = jax.random.normal(ks[2], (b, s, heads * d))

    def loss(q):
        return jnp.sum(flash_attention_bsh(
            q, k, v, num_heads=heads, causal=True) ** 2)

    g_fused = jax.grad(loss)(q)
    monkeypatch.setenv("APEX_TPU_FLASH_BWD", "split")
    g_split = jax.grad(loss)(q)
    np.testing.assert_allclose(np.asarray(g_fused), np.asarray(g_split),
                               rtol=2e-5, atol=2e-5)
    monkeypatch.setenv("APEX_TPU_FLASH_BWD", "spltt")
    with pytest.raises(ValueError, match="APEX_TPU_FLASH_BWD"):
        flash_attention_bsh(q, k, v, num_heads=heads)


def test_flash_bsh_fallback_geometry():
    """head_dim = 48 (not a divisor of 128) routes through the head-major
    kernel and still matches the reference."""
    b, s, heads, d = 2, 12, 2, 48
    hid = heads * d
    ks = jax.random.split(jax.random.PRNGKey(13), 3)
    q = jax.random.normal(ks[0], (b, s, hid))
    k = jax.random.normal(ks[1], (b, s, hid))
    v = jax.random.normal(ks[2], (b, s, hid))
    out = flash_attention_bsh(q, k, v, num_heads=heads, causal=True)
    ref = _ref_bsh(q, k, v, heads, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_bsh_matches_bhsd_kernel():
    """Same inputs through both layouts are numerically identical-ish
    (both fp32 stats, same blockwise order at these shapes)."""
    b, s, heads, d = 2, 32, 4, 32
    hid = heads * d
    ks = jax.random.split(jax.random.PRNGKey(14), 3)
    q = jax.random.normal(ks[0], (b, s, hid))
    k = jax.random.normal(ks[1], (b, s, hid))
    v = jax.random.normal(ks[2], (b, s, hid))
    out = flash_attention_bsh(q, k, v, num_heads=heads, causal=True)
    split = lambda x: jnp.transpose(
        x.reshape(b, s, heads, d), (0, 2, 1, 3))
    out2 = flash_attention(split(q), split(k), split(v), causal=True)
    out2 = jnp.transpose(out2, (0, 2, 1, 3)).reshape(b, s, hid)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out2),
                               rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# (out, lse) variant — the mergeable form ring attention consumes
# ---------------------------------------------------------------------------

def _ref_with_lse(q, k, v, causal=False):
    d = q.shape[-1]
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(
        jnp.float32) / d ** 0.5
    if causal:
        sq, sk = q.shape[2], k.shape[2]
        logits = jnp.where(jnp.tril(jnp.ones((sq, sk), bool)), logits, -1e30)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    p = jnp.exp(logits - lse[..., None])
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v), lse


@pytest.mark.parametrize("causal", [False, True])
def test_flash_with_lse_values_and_grads(causal):
    """out and lse match the reference, and a loss consuming BOTH outputs
    differentiates correctly — the dlse cotangent folds into the backward
    kernels via the delta adjustment."""
    from apex_tpu.kernels.flash_attention import flash_attention_with_lse

    b, h, s, d = 2, 2, 24, 16
    ks = jax.random.split(jax.random.PRNGKey(20), 3)
    q = jax.random.normal(ks[0], (b, h, s, d))
    k = jax.random.normal(ks[1], (b, h, s, d))
    v = jax.random.normal(ks[2], (b, h, s, d))

    out, lse = flash_attention_with_lse(q, k, v, causal=causal)
    ro, rl = _ref_with_lse(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ro),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(rl),
                               rtol=2e-5, atol=2e-5)

    def loss(f):
        def g(q, k, v):
            o, l = f(q, k, v)
            return jnp.sum(jnp.sin(o)) + jnp.sum(jnp.cos(l))
        return g

    gf = jax.grad(loss(lambda q, k, v: flash_attention_with_lse(
        q, k, v, causal=causal)), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss(lambda q, k, v: _ref_with_lse(
        q, k, v, causal=causal)), argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)


def test_flash_with_lse_split_merge_identity():
    """Partials over two disjoint K/V halves, softmax-merged on lse,
    reconstruct full attention exactly (the ring-hop algebra)."""
    from apex_tpu.kernels.flash_attention import flash_attention_with_lse

    b, h, s, d = 1, 2, 32, 16
    ks = jax.random.split(jax.random.PRNGKey(21), 3)
    q = jax.random.normal(ks[0], (b, h, s, d))
    k = jax.random.normal(ks[1], (b, h, s, d))
    v = jax.random.normal(ks[2], (b, h, s, d))

    full, _ = flash_attention_with_lse(q, k, v)
    o1, l1 = flash_attention_with_lse(q, k[:, :, :16], v[:, :, :16])
    o2, l2 = flash_attention_with_lse(q, k[:, :, 16:], v[:, :, 16:])
    m = jnp.maximum(l1, l2)
    w1, w2 = jnp.exp(l1 - m), jnp.exp(l2 - m)
    merged = (o1 * w1[..., None] + o2 * w2[..., None]) / (
        w1 + w2)[..., None]
    np.testing.assert_allclose(np.asarray(merged), np.asarray(full),
                               rtol=2e-5, atol=2e-5)
