"""Fused LayerNorm/RMSNorm kernel tests.

Oracle pattern per apex tests/L0/run_fused_layer_norm (U): compare the
fused kernel against an unfused jax.numpy reference at fp32, over a shape
grid and dtypes, with per-dtype tolerances.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.kernels import layer_norm, rms_norm

TOL = {
    jnp.float32: dict(rtol=1e-5, atol=1e-5),
    jnp.bfloat16: dict(rtol=2e-2, atol=2e-2),
    jnp.float16: dict(rtol=2e-3, atol=2e-3),
}


def ref_layer_norm(x, w, b, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mean = x32.mean(-1, keepdims=True)
    var = ((x32 - mean) ** 2).mean(-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (y * w.astype(jnp.float32) + b.astype(jnp.float32)).astype(x.dtype)


def ref_rms_norm(x, w, eps=1e-5):
    x32 = x.astype(jnp.float32)
    ms = (x32 ** 2).mean(-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(ms + eps) * w.astype(jnp.float32)).astype(x.dtype)


SHAPES = [(4, 96), (3, 7, 128), (16, 1024), (2, 513)]


@pytest.mark.parametrize("shape", SHAPES)
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_layer_norm_forward(shape, dtype):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    h = shape[-1]
    x = jax.random.normal(k1, shape, dtype)
    w = jax.random.normal(k2, (h,), jnp.float32)
    b = jax.random.normal(k3, (h,), jnp.float32)
    got = layer_norm(x, w, b)
    want = ref_layer_norm(x, w, b)
    assert got.dtype == x.dtype
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), **TOL[dtype])


@pytest.mark.parametrize("shape", [(4, 96), (16, 1024)])
def test_layer_norm_grads_match_reference(shape):
    k1, k2, k3, k4 = jax.random.split(jax.random.PRNGKey(1), 4)
    h = shape[-1]
    x = jax.random.normal(k1, shape)
    w = jax.random.normal(k2, (h,))
    b = jax.random.normal(k3, (h,))
    dy = jax.random.normal(k4, shape)

    def fused(x, w, b):
        return jnp.vdot(layer_norm(x, w, b), dy)

    def ref(x, w, b):
        return jnp.vdot(ref_layer_norm(x, w, b), dy)

    gx, gw, gb = jax.grad(fused, argnums=(0, 1, 2))(x, w, b)
    rx, rw, rb = jax.grad(ref, argnums=(0, 1, 2))(x, w, b)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rx), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(rw), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(rb), rtol=1e-4, atol=1e-4)


def test_layer_norm_bf16_io_fp32_params():
    """MixedFusedLayerNorm (U): half I/O, fp32 affine params."""
    x = jax.random.normal(jax.random.PRNGKey(2), (8, 256), jnp.bfloat16)
    w = jnp.ones((256,), jnp.float32) * 1.5
    b = jnp.zeros((256,), jnp.float32)
    y = layer_norm(x, w, b)
    assert y.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(y, np.float32), np.asarray(ref_layer_norm(x, w, b), np.float32),
        rtol=2e-2, atol=2e-2)


def test_layer_norm_no_affine_default():
    x = jax.random.normal(jax.random.PRNGKey(3), (4, 64))
    got = layer_norm(x)
    want = ref_layer_norm(x, jnp.ones(64), jnp.zeros(64))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("shape", [(4, 96), (2, 5, 384)])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_rms_norm_forward(shape, dtype):
    k1, k2 = jax.random.split(jax.random.PRNGKey(4))
    h = shape[-1]
    x = jax.random.normal(k1, shape, dtype)
    w = jax.random.normal(k2, (h,), jnp.float32)
    got = rms_norm(x, w)
    want = ref_rms_norm(x, w)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), **TOL[dtype])


def test_rms_norm_grads_match_reference():
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(5), 3)
    x = jax.random.normal(k1, (8, 192))
    w = jax.random.normal(k2, (192,))
    dy = jax.random.normal(k3, (8, 192))

    gx, gw = jax.grad(lambda x, w: jnp.vdot(rms_norm(x, w), dy), argnums=(0, 1))(x, w)
    rx, rw = jax.grad(lambda x, w: jnp.vdot(ref_rms_norm(x, w), dy), argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rx), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(rw), rtol=1e-4, atol=1e-4)


def test_layer_norm_under_jit_and_vmap():
    x = jax.random.normal(jax.random.PRNGKey(6), (3, 8, 128))
    w = jnp.ones(128)
    b = jnp.zeros(128)
    got = jax.jit(jax.vmap(lambda xi: layer_norm(xi, w, b)))(x)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref_layer_norm(x, w, b)), rtol=1e-5, atol=1e-5)
