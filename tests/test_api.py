"""apex_tpu.serving.api — wire-protocol end-to-end oracles.

A LIVE stdlib HTTP server over a warmed engine, driven through real
sockets (``http.client``), pinned against the same oracles the engine
itself is: an SSE chat stream's token sequence is bit-identical to a
solo ``gpt.generate`` run of the rendered prompt; stop sequences trim
exactly what a host-side reference scan trims; schema-constrained
requests always return parseable, schema-shaped JSON; overload and
terminal-failure map to 429 (+ Retry-After) and 503; an injected
mid-stream fault produces zero duplicate SSE chunks; and the compiled
program caches stay at one entry across the whole varied-request mix
(the wire layer adds no retrace). The dependency-free contract —
``apex_tpu.serving.api`` imports with jax/numpy/torch purged — runs in
a blocked-import subprocess like telemetry's."""

import http.client
import json
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import mesh as mx
from apex_tpu.models import gpt
from apex_tpu.serving.api import (
    ApiServer,
    ByteTokenizer,
    JsonSchemaConstraint,
    render_chat_prompt,
)
from apex_tpu.serving.engine import Engine, EngineConfig
from apex_tpu.serving.resilience import (
    FaultPlan,
    FaultSpec,
    ResilienceConfig,
)
from apex_tpu.serving.scheduler import Scheduler
from apex_tpu.transformer.testing import standalone_gpt_config

#: byte-level codec needs >= 256; the surplus exercises non-byte ids
VOCAB = 320


def _cfg(**overrides):
    base = dict(vocab_size=VOCAB, seq_len=128)
    base.update(overrides)
    return standalone_gpt_config(**base)


def _post(port, path, body, timeout=120):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request("POST", path, json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = resp.read()
    headers = dict(resp.getheaders())
    conn.close()
    return resp.status, data, headers


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("GET", path)
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def _sse_events(raw: bytes):
    """Parse an SSE byte stream into (json payloads, comment lines)."""
    payloads, comments = [], []
    for line in raw.decode("utf-8").split("\n"):
        if line.startswith(": "):
            comments.append(line[2:])
        elif line.startswith("data: ") and line != "data: [DONE]":
            payloads.append(json.loads(line[len("data: "):]))
    assert raw.rstrip().endswith(b"data: [DONE]"), "missing terminator"
    return payloads, comments


def _stream_tokens(payloads, index=0):
    toks = []
    for p in payloads:
        for ch in p.get("choices", ()):
            if ch.get("index", 0) == index:
                toks.extend(ch.get("token_ids") or [])
    return toks


def _solo_generate(cfg, params, mesh, prompt, n_new, *,
                   temperature=0.0, top_k=0, top_p=1.0, seed=None):
    pspecs = gpt.param_specs(cfg)
    key = jax.random.PRNGKey(seed) if seed is not None else None
    out = jax.jit(jax.shard_map(
        lambda p, t: gpt.generate(
            cfg, p, t, n_new, temperature=temperature, top_k=top_k,
            top_p=top_p, key=key, pad_token_id=0),
        mesh=mesh, in_specs=(pspecs, P(None, None)),
        out_specs=P(None, None), check_vma=False))(
            params, jnp.asarray([prompt], jnp.int32))
    return [int(t) for t in np.asarray(out)[0]]


@pytest.fixture(scope="module")
def served(devices8):
    """One warmed engine + scheduler + live ApiServer for the module
    (compile once; every test drives it over real sockets)."""
    from apex_tpu.telemetry import Registry

    cfg = _cfg()
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    engine = Engine(cfg, params, mesh, EngineConfig(
        slots=2, max_prompt_len=48, max_seq_len=128, decode_chunk=1,
        prompt_buckets=(16, 48), admit_batch_sizes=(1, 2)))
    engine.warmup()  # apex: noqa[TIER1-COST]: shared server helper: one warm-cache warmup (~s) serves every live-API test
    registry = Registry()
    sched = Scheduler(engine, registry=registry, pipeline_depth=2)
    tok = ByteTokenizer(cfg.vocab_size)
    server = ApiServer(sched, tok, model="apex-test",
                       registry=registry).start()
    yield dict(server=server, engine=engine, sched=sched, cfg=cfg,
               params=params, mesh=mesh, tok=tok, registry=registry)
    server.stop()
    engine.close()


def _tiny_engine(devices8, fault_plan=None):
    """A minimal fast-compiling engine for fault-path servers."""
    cfg = _cfg(hidden_size=32, num_layers=1, num_heads=2, seq_len=64)
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    params = gpt.init(cfg, jax.random.PRNGKey(1))
    engine = Engine(cfg, params, mesh, EngineConfig(
        slots=2, max_prompt_len=8, max_seq_len=32, decode_chunk=1,
        prompt_buckets=(8,), admit_batch_sizes=(1,)),
        fault_plan=fault_plan)
    engine.warmup()  # apex: noqa[TIER1-COST]: scheduler-level helper on the tiny 1L engine; warm-cache warmup is seconds
    return cfg, params, mesh, engine


# --- happy path: streams, buffering, logprobs, n>1 --------------------------


def test_chat_sse_stream_matches_solo_generate(served):
    """The headline wire oracle: a streamed chat completion's token
    sequence (SSE-reassembled) is bit-identical to solo gpt.generate
    on the rendered prompt, and the streamed text is its decode."""
    s = served
    messages = [{"role": "system", "content": "be brief"},
                {"role": "user", "content": "hi"}]
    status, raw, _ = _post(s["server"].port, "/v1/chat/completions", {
        "messages": messages, "max_tokens": 10, "stream": True,
        "return_token_ids": True})
    assert status == 200
    payloads, _ = _sse_events(raw)
    toks = _stream_tokens(payloads)
    prompt = s["tok"].encode(render_chat_prompt(messages))
    solo = _solo_generate(s["cfg"], s["params"], s["mesh"], prompt, 10)
    assert toks == solo, "wire stream drifted from the solo oracle"
    text = "".join(
        ch["delta"].get("content", "")
        for p in payloads for ch in p["choices"] if "delta" in ch)
    assert text == s["tok"].decode(solo)
    fins = [ch["finish_reason"] for p in payloads
            for ch in p["choices"] if ch.get("finish_reason")]
    assert fins == ["length"]


def test_completions_buffered_usage_and_logprobs(served):
    s = served
    status, raw, _ = _post(s["server"].port, "/v1/completions", {
        "prompt": "ab", "max_tokens": 6, "logprobs": 1, "echo": True,
        "return_token_ids": True})
    assert status == 200
    d = json.loads(raw)
    assert d["object"] == "text_completion"
    (choice,) = d["choices"]
    assert choice["text"].startswith("ab")  # echo
    assert len(choice["token_ids"]) == 6
    lps = choice["logprobs"]["token_logprobs"]
    assert len(lps) == 6
    assert all(np.isfinite(lp) and lp <= 0.0 for lp in lps)
    assert d["usage"] == {"prompt_tokens": 2, "completion_tokens": 6,
                          "total_tokens": 8}


def test_token_id_prompt_and_n_sampling(served):
    """Legacy token-id prompts; n=2 fans into two slots sharing the
    prompt with derived seeds — two distinct sampled streams merged
    into one indexed response."""
    s = served
    status, raw, _ = _post(s["server"].port, "/v1/completions", {
        "prompt": [5, 6, 7], "max_tokens": 6, "n": 2,
        "temperature": 0.9, "top_k": 20, "seed": 7,
        "return_token_ids": True})
    assert status == 200
    d = json.loads(raw)
    ids = {c["index"]: c["token_ids"] for c in d["choices"]}
    assert set(ids) == {0, 1}
    assert ids[0] != ids[1], "choices shared a PRNG stream"
    # choice 0 is exactly a seed=7 solo run
    solo = _solo_generate(s["cfg"], s["params"], s["mesh"], [5, 6, 7],
                          6, temperature=0.9, top_k=20, seed=7)
    assert ids[0] == solo
    assert d["usage"]["completion_tokens"] == 12


def test_validation_errors_are_400(served):
    port = served["server"].port
    for body, frag in [
            ({}, "messages"),
            ({"messages": [{"role": "u", "content": "x"}],
              "top_k": 5}, "temperature"),
            ({"messages": [{"role": "u", "content": "x"}],
              "n": 99}, "n"),
            ({"messages": [{"role": "u", "content": "x" * 500}]},
             "admits at most"),
    ]:
        status, raw, _ = _post(port, "/v1/chat/completions", body)
        assert status == 400, raw
        err = json.loads(raw)["error"]
        assert err["type"] == "invalid_request_error"
        assert frag in (err.get("param") or "") + err["message"]


def test_models_and_healthz_routes(served):
    status, raw = _get(served["server"].port, "/v1/models")
    assert status == 200
    assert json.loads(raw)["data"][0]["id"] == "apex-test"
    status, raw = _get(served["server"].port, "/healthz")
    assert status == 200 and raw.startswith(b"ok")


# --- stop sequences ----------------------------------------------------------


def _reference_trim(stream, stops):
    """Independent host reference: cut the stream at the first point a
    stop sequence completes, excluding the stop itself."""
    for i in range(len(stream)):
        for stop in stops:
            if i + 1 >= len(stop) and \
                    stream[i + 1 - len(stop):i + 1] == list(stop):
                return stream[:i + 1 - len(stop)], True
    return list(stream), False


def test_stop_sequence_trim_parity(served):
    """Wire-level stop: the served stream equals the solo-generate
    stream trimmed at the first stop occurrence (stop tokens never
    reach the wire), finish_reason 'stop'."""
    s = served
    prompt = [11, 12, 13]
    solo = _solo_generate(s["cfg"], s["params"], s["mesh"], prompt, 12)
    stop = solo[3:5]  # guaranteed to occur
    expect, matched = _reference_trim(solo, [stop])
    assert matched
    status, raw, _ = _post(s["server"].port, "/v1/completions", {
        "prompt": prompt, "max_tokens": 12, "stream": True,
        "stop_token_ids": [stop], "return_token_ids": True})
    assert status == 200
    payloads, _ = _sse_events(raw)
    toks = _stream_tokens(payloads)
    assert toks == expect, f"trimmed stream {toks} != expected {expect}"
    fins = [ch["finish_reason"] for p in payloads
            for ch in p["choices"] if ch.get("finish_reason")]
    assert fins == ["stop"]


def test_stop_string_via_text_roundtrip(served):
    """ASCII stop strings compile to byte sequences; a stop that never
    occurs leaves the stream untouched (held tokens flush at the
    device finish)."""
    s = served
    prompt = [40, 41]
    solo = _solo_generate(s["cfg"], s["params"], s["mesh"], prompt, 8)
    status, raw, _ = _post(s["server"].port, "/v1/completions", {
        "prompt": prompt, "max_tokens": 8,
        "stop": "NEVER", "return_token_ids": True})
    assert status == 200
    d = json.loads(raw)
    assert d["choices"][0]["token_ids"] == solo
    assert d["choices"][0]["finish_reason"] == "length"


# --- schema-constrained decoding --------------------------------------------

_SCHEMA = {
    "type": "object",
    "properties": {
        "name": {"type": "string", "maxLength": 8},
        "age": {"type": "integer"},
        "tags": {"type": "array",
                 "items": {"type": "string", "maxLength": 6},
                 "minItems": 1, "maxItems": 2},
        "kind": {"enum": ["x", "y"]},
    },
}


def test_constrained_json_schema_always_valid(served):
    """Greedy AND sampled constrained requests return parseable JSON
    matching the schema shape, finishing via the constraint (reason
    'stop'), whatever the logits wanted."""
    s = served
    for extra in ({}, {"temperature": 0.9, "seed": 3}):
        status, raw, _ = _post(s["server"].port, "/v1/chat/completions", {
            "messages": [{"role": "user", "content": "emit json"}],
            "max_tokens": 90,
            "response_format": {
                "type": "json_schema",
                "json_schema": {"schema": _SCHEMA}},
            **extra})
        assert status == 200, raw
        choice = json.loads(raw)["choices"][0]
        assert choice["finish_reason"] == "stop"
        v = json.loads(choice["message"]["content"])
        assert set(v) == {"name", "age", "tags", "kind"}
        assert isinstance(v["name"], str) and len(v["name"]) <= 8
        assert isinstance(v["age"], int)
        assert isinstance(v["tags"], list) and 1 <= len(v["tags"]) <= 2
        assert all(isinstance(t, str) for t in v["tags"])
        assert v["kind"] in ("x", "y")


def test_constrained_json_object_mode(served):
    s = served
    status, raw, _ = _post(s["server"].port, "/v1/chat/completions", {
        "messages": [{"role": "user", "content": "emit json"}],
        "max_tokens": 100,
        "response_format": {"type": "json_object",
                            "bounds": {"max_string_len": 6,
                                       "max_keys": 2, "max_items": 2,
                                       "max_depth": 1}}})
    assert status == 200, raw
    choice = json.loads(raw)["choices"][0]
    assert choice["finish_reason"] == "stop"
    assert isinstance(json.loads(choice["message"]["content"]), dict)


def test_invalid_schema_is_400_not_connection_drop(served):
    """A schema that parses as a dict but fails automaton compile
    (empty enum, maxItems < minItems) must come back as a clean 400,
    not an uncaught exception dropping the socket — and a max_tokens
    below the schema's closure bound is rejected up front instead of
    truncating mid-value (the always-valid guarantee is enforced)."""
    port = served["server"].port
    for bad in ({"enum": []},
                {"type": "array", "minItems": 5, "maxItems": 2}):
        status, raw, _ = _post(port, "/v1/chat/completions", {
            "messages": [{"role": "user", "content": "x"}],
            "max_tokens": 8,
            "response_format": {"type": "json_schema",
                                "json_schema": {"schema": bad}}})
        assert status == 400, raw
        err = json.loads(raw)["error"]
        assert err["param"] == "response_format"
        assert "rejected" in err["message"]
    status, raw, _ = _post(port, "/v1/chat/completions", {
        "messages": [{"role": "user", "content": "x"}],
        "max_tokens": 3,
        "response_format": {"type": "json_schema",
                            "json_schema": {"schema": _SCHEMA}}})
    assert status == 400, raw
    assert json.loads(raw)["error"]["code"] == \
        "max_tokens_below_schema_bound"


def test_constraint_bounds_and_prefix_enums():
    """Pure-automaton oracles: token_bound() dominates every random
    walk's actual length, and non-prefix-free enums (1 vs 12) keep
    BOTH members reachable (the shorter closes via the parent's
    terminator or the end token, the longer stays offered)."""
    import random

    schema = {"type": "object", "properties": {
        "n": {"enum": [1, 12, 3.5]},
        "s": {"type": "string", "maxLength": 5}}}
    c = JsonSchemaConstraint(schema)
    bound = c.token_bound()
    rng = random.Random(7)
    seen = set()
    for _ in range(120):
        c.reset()
        out = []
        while not c.done:
            b = rng.choice(c.allowed_tokens())
            c.advance(b)
            out.append(b)
        assert len(out) <= bound, (len(out), bound)
        v = json.loads(bytes(out).decode())
        assert v["n"] in (1, 12, 3.5)
        seen.add(v["n"])
    assert seen == {1, 12, 3.5}, f"enum members unreachable: {seen}"
    # bare scalar with an end token: the model can stop a value whose
    # grammar could continue
    c = JsonSchemaConstraint({"type": "integer"}, end_token_id=300)
    c.advance(ord("7"))
    assert 300 in c.allowed_tokens()
    c.advance(300)
    assert c.done


def test_recompile_flat_across_varied_requests(served):
    """The acceptance pin: after the whole varied mix above (stop, n,
    logprobs, schema, sampled/greedy) every compiled program cache is
    still at one entry, and a guard stays silent through one more mixed
    round served entirely over the wire."""
    s = served
    with s["engine"].recompile_guard():
        _post(s["server"].port, "/v1/completions", {
            "prompt": [9, 9], "max_tokens": 4,
            "stop_token_ids": [[1, 2, 3]], "logprobs": 1})
        _post(s["server"].port, "/v1/chat/completions", {
            "messages": [{"role": "user", "content": "again"}],
            "max_tokens": 30, "n": 2, "temperature": 0.8, "seed": 11,
            "response_format": {"type": "json_object"}})
    sizes = s["engine"].compiled_cache_sizes()
    assert all(v == 1 for v in sizes.values() if v is not None), sizes


# --- overload + failure mapping ---------------------------------------------


def test_queue_full_429_and_engine_failed_503(devices8):
    """PR-5 resilience → wire codes: an injected queue flood maps to
    429 with a Retry-After header and a rate_limit_error body; a
    terminally failed health machine maps to 503 on submit and on
    /healthz."""
    plan = FaultPlan([FaultSpec("submit", 0, "flood")])
    cfg, params, mesh, engine = _tiny_engine(devices8, fault_plan=plan)
    sched = Scheduler(engine)
    server = ApiServer(sched, ByteTokenizer(cfg.vocab_size)).start()
    try:
        status, raw, headers = _post(server.port, "/v1/completions", {
            "prompt": [1, 2], "max_tokens": 4})
        assert status == 429, raw
        err = json.loads(raw)["error"]
        assert err["type"] == "rate_limit_error"
        assert "Retry-After" in headers
        assert len(plan.injected) == 1
        # terminal health: submissions and probes both answer 503
        sched.health.fail("test: terminal")
        status, raw, _ = _post(server.port, "/v1/completions", {
            "prompt": [1, 2], "max_tokens": 4})
        assert status == 503, raw
        assert json.loads(raw)["error"]["type"] == "server_error"
        status, raw = _get(server.port, "/healthz")
        assert status == 503
    finally:
        server.stop()
        engine.close()


def test_sse_no_duplicate_chunks_under_fault(devices8):
    """The wire half of the replay guarantee: a fetch-seam fault mid
    stream produces a retry comment, zero duplicate token chunks, and
    a final stream bit-identical to a fault-free engine's."""
    cfg, params, mesh, clean_eng = _tiny_engine(devices8)
    sched_clean = Scheduler(clean_eng)
    server_clean = ApiServer(
        sched_clean, ByteTokenizer(cfg.vocab_size)).start()
    body = {"prompt": [3, 4, 5], "max_tokens": 8, "stream": True,
            "return_token_ids": True}
    try:
        _, raw, _ = _post(server_clean.port, "/v1/completions", body)
        clean_toks = _stream_tokens(_sse_events(raw)[0])
        assert len(clean_toks) == 8
    finally:
        server_clean.stop()
        clean_eng.close()

    plan = FaultPlan([FaultSpec("fetch", 2, "error")])
    _, _, _, fault_eng = _tiny_engine(devices8, fault_plan=plan)
    sched = Scheduler(fault_eng, resilience=ResilienceConfig(
        backoff_base_s=0.001))
    server = ApiServer(sched, ByteTokenizer(cfg.vocab_size)).start()
    try:
        status, raw, _ = _post(server.port, "/v1/completions", body)
        assert status == 200
        payloads, comments = _sse_events(raw)
        toks = _stream_tokens(payloads)
        assert len(plan.injected) == 1, "fault did not fire"
        assert any("retrying" in c for c in comments)
        assert toks == clean_toks, (
            f"fault stream {toks} drifted from clean {clean_toks} "
            f"(duplicate or lost SSE chunks)")
    finally:
        server.stop()
        fault_eng.close()


# --- dependency-free contract ------------------------------------------------


def test_api_imports_stdlib_only(tmp_path):
    """The front end must add NOTHING beyond the stdlib: load the
    parent packages (the baked jax toolchain), then purge jax/numpy/
    scipy/torch from sys.modules AND block any re-import — every
    serving.api module must import and run its pure logic anyway."""
    code = """
import sys

import apex_tpu.serving  # parents (jax toolchain) load normally

BLOCKED = ("jax", "jaxlib", "numpy", "scipy", "torch", "tensorboard")


class _Blocker:
    def find_spec(self, name, path=None, target=None):
        if name.split(".")[0] in BLOCKED:
            raise ImportError(f"blocked by test: {name}")
        return None


for mod in list(sys.modules):
    if mod.split(".")[0] in BLOCKED:
        del sys.modules[mod]
sys.meta_path.insert(0, _Blocker())

import apex_tpu.serving.api as api
import apex_tpu.serving.api.tokenizer
import apex_tpu.serving.api.protocol
import apex_tpu.serving.api.constrain
import apex_tpu.serving.api.server

tok = api.ByteTokenizer(320)
assert tok.decode(tok.encode("hello")) == "hello"
dec = tok.stream_decoder()
assert "".join(dec.push(t) for t in tok.encode("héllo")) == "héllo"

from apex_tpu.serving.api.protocol import parse_chat_request, sse
p = parse_chat_request({"messages": [{"role": "user", "content": "x"}],
                        "stop": ["end"], "max_tokens": 4})
assert p.stop == ["end"] and p.max_tokens == 4
assert sse({"a": 1}) == b'data: {"a":1}\\n\\n'

c = api.JsonSchemaConstraint({"type": "object", "properties":
                              {"k": {"type": "integer"}}})
out = []
while not c.done:
    b = min(c.allowed_tokens())
    c.advance(b)
    out.append(b)
import json as _json
assert _json.loads(bytes(out).decode())["k"] is not None

assert not any(m.split(".")[0] in BLOCKED for m in sys.modules)
print("API_DEP_FREE_OK")
"""
    import os

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr
    assert "API_DEP_FREE_OK" in out.stdout


# --- scheduler-level stop/constraint/logprob oracles (no HTTP) ---------------


def test_scheduler_stop_across_chunk_boundary(devices8):
    """Engine-level stop with decode_chunk=4: a stop sequence whose
    tokens split across chunk boundaries still trims exactly, and the
    event stream never contains a trimmed token."""
    from apex_tpu.serving import Request

    cfg = _cfg(hidden_size=32, num_layers=1, num_heads=2, seq_len=64)
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    params = gpt.init(cfg, jax.random.PRNGKey(1))
    solo = _solo_generate(cfg, params, mesh, [3, 4, 5], 12)
    stop = solo[5:7]
    expect, matched = _reference_trim(solo, [stop])
    assert matched
    engine = Engine(cfg, params, mesh, EngineConfig(
        slots=2, max_prompt_len=8, max_seq_len=32, decode_chunk=4,
        prompt_buckets=(8,), admit_batch_sizes=(1,)))
    engine.warmup()  # apex: noqa[TIER1-COST]: tiny 1L engine; warm-cache warmup is seconds and the stop oracle needs warmed variants
    try:
        sched = Scheduler(engine, pipeline_depth=2)
        sched.submit(Request("r0", [3, 4, 5], max_tokens=12,
                             stop=[stop]))
        sched.run_until_idle()
        comp = sched.completions["r0"]
        assert comp.tokens == expect
        assert comp.finish_reason == "stop"
        assert len(comp.logprobs) == len(comp.tokens)
        streamed = [e.token for e in sched.pop_events()
                    if e.token is not None]
        assert streamed == expect
    finally:
        engine.close()


def test_scheduler_constraint_forces_token_sequence(devices8):
    """The whole mask path, oracled end to end: a single-value enum
    constraint forces the engine to emit exactly that JSON literal's
    bytes, regardless of what the unconstrained logits preferred."""
    from apex_tpu.serving import Request

    cfg = _cfg(hidden_size=32, num_layers=1, num_heads=2, seq_len=64)
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    params = gpt.init(cfg, jax.random.PRNGKey(1))
    engine = Engine(cfg, params, mesh, EngineConfig(
        slots=2, max_prompt_len=8, max_seq_len=32, decode_chunk=1,
        prompt_buckets=(8,), admit_batch_sizes=(1,)))
    engine.warmup()  # apex: noqa[TIER1-COST]: tiny 1L engine; constraint oracle needs warmed chunk=1 variants
    try:
        sched = Scheduler(engine)
        forced = list(b'"ab"')
        sched.submit(Request(
            "r0", [3, 4, 5], max_tokens=12,
            constraint=JsonSchemaConstraint({"enum": ["ab"]})))
        sched.run_until_idle()
        comp = sched.completions["r0"]
        assert comp.tokens == forced
        assert comp.finish_reason == "stop"
        # constrained requests need chunk=1 — enforced at submit
        with Engine(cfg, params, mesh, EngineConfig(
                slots=2, max_prompt_len=8, max_seq_len=32, decode_chunk=2,
                prompt_buckets=(8,), admit_batch_sizes=(1,))) as engine8:
            engine8.warmup()  # apex: noqa[TIER1-COST]: second tiny engine for the chunk>1 rejection arm; warm-cache warmup is seconds
            with pytest.raises(ValueError, match="decode_chunk"):
                Scheduler(engine8).submit(Request(
                    "r1", [3], max_tokens=4,
                    constraint=JsonSchemaConstraint({"enum": ["a"]})))
    finally:
        engine.close()
