"""Flat-buffer packing tests (apex_C flatten/unflatten parity (U))."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import multi_tensor as mt


def make_tree():
    k = jax.random.PRNGKey(0)
    return {
        "w1": jax.random.normal(k, (17, 9)),
        "b1": jnp.arange(9.0),
        "emb": jax.random.normal(k, (5, 3)).astype(jnp.bfloat16),
        "scalar": jnp.float32(3.0),
        "step": jnp.int32(7),
    }


def test_pack_unpack_roundtrip():
    tree = make_tree()
    bufs, layout = mt.pack(tree)
    out = mt.unpack(bufs, layout)
    assert jax.tree.structure(out) == jax.tree.structure(tree)
    for a, b in zip(jax.tree.leaves(tree), jax.tree.leaves(out)):
        assert a.dtype == b.dtype and a.shape == b.shape
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_buffers_grouped_by_dtype_and_padded():
    bufs, layout = mt.pack(make_tree())
    assert len(bufs) == 3  # f32, bf16, i32
    for buf, size, used in zip(bufs, layout.group_sizes, layout.group_used):
        assert buf.shape == (size,)
        assert size % mt.LANE == 0 and size >= used
        # padding is zero
        np.testing.assert_array_equal(np.asarray(buf[used:]), 0)


def test_layout_reuse_aligns_grads_with_params():
    params = {"a": jnp.ones((4, 4)), "b": jnp.ones(3)}
    grads = jax.tree.map(lambda p: p * 2, params)
    pbufs, layout = mt.pack(params)
    gbufs, _ = mt.pack(grads, layout)
    np.testing.assert_allclose(np.asarray(gbufs[0]), 2 * np.asarray(pbufs[0]))


def test_layout_mismatch_raises():
    params = {"a": jnp.ones((4, 4))}
    _, layout = mt.pack(params)
    with pytest.raises(ValueError):
        mt.pack({"a": jnp.ones((2, 2))}, layout)
    with pytest.raises(ValueError):
        mt.pack({"a": jnp.ones((4, 4)), "b": jnp.ones(1)}, layout)


def test_pack_is_jittable():
    params = {"a": jnp.ones((4, 4)), "b": jnp.full((3,), 2.0)}
    _, layout = mt.pack(params)

    @jax.jit
    def f(tree):
        bufs, _ = mt.pack(tree, layout)
        return mt.unpack([b * 10 for b in bufs], layout)

    out = f(params)
    np.testing.assert_allclose(np.asarray(out["a"]), 10 * np.ones((4, 4)))
    np.testing.assert_allclose(np.asarray(out["b"]), 20 * np.ones(3))


def test_flatten_dense_tensors_parity():
    ts = [jnp.ones((2, 3)), jnp.arange(4.0)]
    flat = mt.flatten_dense_tensors(ts)
    assert flat.shape == (10,)
    back = mt.unflatten_dense_tensors(flat * 2, ts)
    np.testing.assert_allclose(np.asarray(back[0]), 2 * np.ones((2, 3)))
    np.testing.assert_allclose(np.asarray(back[1]), 2 * np.arange(4.0))
    with pytest.raises(ValueError):
        mt.flatten_dense_tensors([jnp.ones(2), jnp.ones(2, jnp.bfloat16)])
