"""BERT and ResNet model families (BASELINE configs #1-#3).

BERT: TP parity vs unsharded, MLM mask weighting. ResNet: shapes, SyncBN
state updates, one FusedSGD step reduces loss.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu import mesh as mx
from apex_tpu.models import bert, resnet
from apex_tpu.optimizers import fused_sgd

BCFG = dict(vocab_size=96, hidden_size=64, num_layers=2, num_heads=4,
            seq_len=32, compute_dtype=jnp.float32, remat=False)


def smap(f, mesh, in_specs, out_specs):
    return jax.jit(jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False))


def _bert_loss(cfg, params, mesh, specs, tok, tgt, mask):
    return smap(
        lambda p, t, y, m: bert.mlm_loss(cfg, p, t, y, m),
        mesh, (specs, P(), P(), P()), P())(params, tok, tgt, mask)


def test_bert_tp_parity(devices8):
    tok = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 96)
    tgt = jnp.roll(tok, -1, 1)
    mask = (jax.random.uniform(jax.random.PRNGKey(2), (4, 32)) < 0.15
            ).astype(jnp.int32)

    cfg = bert.BertConfig(**BCFG)
    params = bert.init(cfg, jax.random.PRNGKey(0))

    mesh1 = mx.build_mesh(tp=1, devices=devices8[:1])
    ref = float(_bert_loss(cfg, params, mesh1, bert.param_specs(cfg),
                           tok, tgt, mask))

    for sp in (False, True):
        cfg4 = bert.BertConfig(**{**BCFG, "sequence_parallel": sp})
        mesh4 = mx.build_mesh(tp=4, devices=devices8[:4])
        out = float(_bert_loss(cfg4, params, mesh4, bert.param_specs(cfg4),
                               tok, tgt, mask))
        np.testing.assert_allclose(out, ref, rtol=2e-5)


def test_bert_mask_weighting(devices8):
    cfg = bert.BertConfig(**BCFG)
    params = bert.init(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    specs = bert.param_specs(cfg)
    tok = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 96)
    # all-mask vs single-position mask give different losses
    full = _bert_loss(cfg, params, mesh, specs, tok, tok,
                      jnp.ones((2, 32), jnp.int32))
    one = jnp.zeros((2, 32), jnp.int32).at[:, 0].set(1)
    single = _bert_loss(cfg, params, mesh, specs, tok, tok, one)
    assert not np.isclose(float(full), float(single))


def test_resnet_forward_and_step():
    cfg = resnet.ResNetConfig(depth=26, num_classes=10,
                              compute_dtype=jnp.float32, bn_axis=None)
    params, state = resnet.init(cfg, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    y = jax.random.randint(jax.random.PRNGKey(2), (2,), 0, 10)

    logits, ns = jax.jit(
        lambda p, s, x: resnet.forward(cfg, p, s, x))(params, state, x)
    assert logits.shape == (2, 10)
    # BN state advanced
    a = float(state["bn_stem"]["mean"].sum())
    b = float(ns["bn_stem"]["mean"].sum())
    assert a != b

    opt = fused_sgd(0.01)

    @jax.jit
    def step(params, state, opt_state):
        (l, ns), g = jax.value_and_grad(
            lambda p: resnet.loss(cfg, p, state, x, y), has_aux=True)(params)
        new_p, opt_state = opt.step(g, opt_state, params)
        return l, new_p, ns, opt_state

    opt_state = opt.init(params)
    losses = []
    for _ in range(5):
        l, params, state, opt_state = step(params, state, opt_state)
        losses.append(float(l))
    assert losses[-1] < losses[0]


def test_resnet_syncbn_matches_big_batch(devices8):
    """SyncBN over dp=4 on batch shards == local BN on the full batch."""
    cfg_sync = resnet.ResNetConfig(depth=26, num_classes=4, bn_axis="dp",
                                   compute_dtype=jnp.float32)
    cfg_local = resnet.ResNetConfig(depth=26, num_classes=4, bn_axis=None,
                                    compute_dtype=jnp.float32)
    params, state = resnet.init(cfg_local, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 16, 16, 3))

    ref, _ = jax.jit(lambda p, s, x: resnet.forward(cfg_local, p, s, x))(
        params, state, x)

    mesh = mx.build_mesh(tp=1, devices=devices8[:4])
    out, _ = smap(
        lambda p, s, x: resnet.forward(cfg_sync, p, s, x),
        mesh, (P(), P(), P("dp")), (P("dp"), P()))(params, state, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_bert_perf_knobs_forwarded():
    """BertConfig forwards the measured perf knobs into the core stack."""
    from apex_tpu.models import bert

    cfg = bert.BertConfig(vocab_size=64, hidden_size=32, num_layers=2,
                          num_heads=2, seq_len=16, attn_impl="flash",
                          ln_impl="xla", remat_policy="qkv_fc1_attn")
    core = cfg.core()
    assert core.attn_impl == "flash" and core.ln_impl == "xla"
    assert core.remat_policy == "qkv_fc1_attn" and not core.causal


def test_bert_train_step_builder(devices8):
    """make_mlm_train_step: one-call amp+optimizer+parallelism trainer —
    SP and fsdp variants train identically to the replicated baseline."""
    from apex_tpu.amp import ScalerConfig
    from apex_tpu.optimizers import fused_sgd

    def run(tp=1, **kw):
        cfg = bert.BertConfig(
            vocab_size=96, hidden_size=64, num_layers=2, num_heads=4,
            seq_len=32, type_vocab_size=2, compute_dtype=jnp.float32,
            **kw)
        mesh = mx.build_mesh(tp=tp, devices=devices8)
        init_fn, step_fn = bert.make_mlm_train_step(
            cfg, mesh, fused_sgd(0.1, layout="tree"),
            ScalerConfig(enabled=False), clip_grad_norm=5.0)
        state = init_fn(jax.random.PRNGKey(0))
        tok = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 96)
        mask = (jax.random.uniform(jax.random.PRNGKey(2), (8, 32))
                < 0.3).astype(jnp.int32)
        losses = []
        for _ in range(3):
            state, m = step_fn(state, tok, tok, mask)
            losses.append(float(m["loss"]))
        assert np.isfinite(m["grad_norm"])
        return losses

    # same-mesh comparisons are tight (only the feature under test
    # differs); tp=2 vs tp=1 adds matmul-split reduction-order noise
    ref1 = run()
    ref2 = run(tp=2)
    np.testing.assert_allclose(ref2, ref1, rtol=2e-3)
    np.testing.assert_allclose(run(tp=2, sequence_parallel=True), ref2,
                               rtol=2e-4)
    np.testing.assert_allclose(run(fsdp=True), ref1, rtol=2e-4)
    np.testing.assert_allclose(
        run(tp=2, fsdp=True, sequence_parallel=True), ref2, rtol=2e-4)


def test_resnet_train_step_builder(devices8):
    """make_train_step for ResNet: SyncBN over dp=8 shards must train
    exactly like one device seeing the full batch (the SyncBatchNorm
    contract at trainer level), and BN stats ride TrainState.extra."""
    from apex_tpu.amp import ScalerConfig

    img = jax.random.normal(jax.random.PRNGKey(1), (8, 32, 32, 3))
    lbl = jax.random.randint(jax.random.PRNGKey(2), (8,), 0, 10)

    def run(devices, bn_axis):
        # depth 26 + fp32: deep untrained stacks at toy resolution are
        # chaotically conditioned (1e-5 BN-stat noise amplifies ~1e3
        # per stage through tiny-variance normalizations — fp64 pins
        # the math as exact); 26 layers exercise every code path at
        # tolerances that still PROVE parity
        cfg = resnet.ResNetConfig(depth=26, num_classes=10,
                                  bn_axis=bn_axis,
                                  compute_dtype=jnp.float32)
        mesh = mx.build_mesh(tp=1, devices=devices)
        # small lr: at 0.1 the untrained net's first step explodes the
        # loss ~10x, amplifying fp reduction-order noise into percents
        init_fn, step_fn = resnet.make_train_step(
            cfg, mesh, fused_sgd(1e-3, momentum=0.9, layout="tree"),
            ScalerConfig(enabled=False))
        state = init_fn(jax.random.PRNGKey(0))
        state, m = step_fn(state, img, lbl)
        return (float(m["loss"]), jax.device_get(state.params),
                jax.device_get(state.extra))

    # one step: loss, updated params, and BN stats are the
    # well-conditioned quantities (an untrained 50-layer stack is
    # chaotically sensitive — 1e-5 param noise grows ~1e3 per extra
    # step through the tiny-variance BNs, so multi-step loss curves
    # are not comparable at useful tolerances)
    ref_loss, ref_p, ref_bn = run(devices8[:1], None)  # full batch
    sync_loss, sync_p, sync_bn = run(devices8, "dp")   # 8 shards+SyncBN
    np.testing.assert_allclose(sync_loss, ref_loss, rtol=2e-4)
    for a, b in zip(jax.tree.leaves(ref_p), jax.tree.leaves(sync_p)):
        # atol covers lr * (per-element fp32 BN-stat noise) on the
        # zero-initialized leaves whose update IS that small noise
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=1e-4)
    for a, b in zip(jax.tree.leaves(ref_bn), jax.tree.leaves(sync_bn)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-5)
    # local BN over shards diverges from the full-batch stats (the
    # difference SyncBatchNorm exists to remove) but still trains
    local_loss, _, _ = run(devices8, None)
    assert np.isfinite(local_loss)
