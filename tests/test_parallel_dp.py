"""Data-parallel runtime tests.

Parity model: apex tests/distributed/DDP + synced_batchnorm suites (U) on
the CPU-simulated mesh. Includes the overlap-equivalence regression (flat
bucketed reduce == per-tensor reduce) that replaces apex's
ddp_race_condition_test.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import mesh as mx
from apex_tpu.parallel import (
    DistributedDataParallel,
    Reducer,
    SyncBatchNorm,
    allreduce_gradients,
    flat_dist_call,
    sync_batch_norm,
)


@pytest.fixture()
def dp8(devices8):
    return mx.build_mesh(tp=1, pp=1, devices=devices8)


def smap(f, mesh, in_specs, out_specs):
    return jax.jit(jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False))


def test_allreduce_gradients_average(dp8):
    grads = {"w": jnp.arange(8.0).reshape(8, 1), "b": jnp.ones((8, 2))}

    out = smap(lambda g: allreduce_gradients(g), dp8,
               ({"w": P("dp", None), "b": P("dp", None)},),
               {"w": P("dp", None), "b": P("dp", None)})(grads)
    # every shard's value becomes the mean over shards: w → mean(0..7)=3.5
    np.testing.assert_allclose(np.asarray(out["w"]), 3.5 * np.ones((8, 1)))
    np.testing.assert_allclose(np.asarray(out["b"]), np.ones((8, 2)))


def test_allreduce_fp32_upcast_keeps_dtype(dp8):
    g = jnp.ones((8, 4), jnp.bfloat16)
    out = smap(lambda g: allreduce_gradients(g, allreduce_always_fp32=True),
               dp8, (P("dp", None),), P("dp", None))(g)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32), 1.0)


def test_flat_dist_call_matches_per_tensor(dp8):
    """Overlap-equivalence regression: one flat-buffer reduce must equal
    per-tensor reduce exactly (apex ddp_race_condition_test analogue)."""
    tree = {
        "a": jnp.arange(8 * 3.0).reshape(8, 3),
        "b": jnp.arange(8 * 5.0).reshape(8, 5) * 0.1,
        "c": jnp.ones((8, 2), jnp.bfloat16),
    }
    specs = {k: P("dp", None) for k in tree}
    flat = smap(lambda t: flat_dist_call(t, op="pmean"), dp8, (specs,), specs)(tree)
    per = smap(lambda t: allreduce_gradients(t), dp8, (specs,), specs)(tree)
    for k in tree:
        np.testing.assert_array_equal(np.asarray(flat[k]), np.asarray(per[k]))


def test_flat_dist_call_broadcast(dp8):
    x = jnp.arange(8.0).reshape(8, 1)
    out = smap(lambda t: flat_dist_call(t, op="broadcast", src=2), dp8,
               (P("dp", None),), P("dp", None))(x)
    np.testing.assert_allclose(np.asarray(out), 2.0 * np.ones((8, 1)))


def test_ddp_wrap_and_no_sync_accumulation(dp8):
    """DDP-reduced grads == full-batch grads; two accumulated microbatches
    == one big batch (delay_allreduce semantics (U))."""
    params = {"w": jnp.array([[1.0], [2.0]])}  # (2, 1)
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 2))
    y = jax.random.normal(jax.random.PRNGKey(1), (16, 1))

    def loss(p, x, y):
        return jnp.mean((x @ p["w"] - y) ** 2)

    ddp = DistributedDataParallel()
    grad_fn = jax.grad(loss)

    def step(p, x, y):
        return ddp.wrap_grad_fn(grad_fn)(p, x, y)

    g = smap(step, dp8, ({"w": P()}, P("dp", None), P("dp", None)),
             {"w": P()})(params, x, y)
    gref = jax.grad(loss)(params, x, y)
    np.testing.assert_allclose(np.asarray(g["w"]), np.asarray(gref["w"]),
                               rtol=1e-6, atol=1e-6)

    # accumulation: shard the batch in two halves per rank
    def step_accum(p, x1, y1, x2, y2):
        g1 = ddp.no_sync(grad_fn)(p, x1, y1)
        g = ddp.wrap_grad_fn(grad_fn)(p, x2, y2, accumulated=g1)
        return g

    g2 = smap(step_accum, dp8,
              ({"w": P()}, P("dp", None), P("dp", None), P("dp", None), P("dp", None)),
              {"w": P()})(params, x[:8], y[:8], x[8:], y[8:])
    # sum of two half-batch mean-grads = 2x grad of mean over half batches
    ref2 = jax.tree.map(jnp.add, jax.grad(loss)(params, x[:8], y[:8]),
                        jax.grad(loss)(params, x[8:], y[8:]))
    np.testing.assert_allclose(np.asarray(g2["w"]), np.asarray(ref2["w"]),
                               rtol=1e-6, atol=1e-6)


def test_reducer_broadcast(dp8):
    r = Reducer()
    x = jnp.arange(8.0).reshape(8, 1)
    out = smap(lambda t: r.broadcast(t), dp8, (P("dp", None),), P("dp", None))(x)
    np.testing.assert_allclose(np.asarray(out), 0.0 * np.ones((8, 1)))


# -- SyncBatchNorm ---------------------------------------------------------
def _bn_ref(x, scale, bias, eps=1e-5):
    # full-batch batchnorm over (N, H, W) for NCHW
    mean = x.mean(axis=(0, 2, 3))
    var = x.var(axis=(0, 2, 3))
    y = (x - mean[None, :, None, None]) / np.sqrt(var[None, :, None, None] + eps)
    return y * scale[None, :, None, None] + bias[None, :, None, None]


def test_syncbn_matches_full_batch(dp8):
    n, c, h, w = 16, 4, 3, 3
    x = jax.random.normal(jax.random.PRNGKey(2), (n, c, h, w))
    scale = jnp.array([1.0, 2.0, 0.5, 1.5])
    bias = jnp.array([0.0, 1.0, -1.0, 0.5])
    bn = SyncBatchNorm(c)
    params, state = bn.init()
    params = {"scale": scale, "bias": bias}

    def f(p, s, x):
        y, ns = bn.apply(p, s, x)
        return y, ns

    pspec, sspec = bn.specs
    y, ns = smap(f, dp8, (pspec, sspec, P("dp", None, None, None)),
                 (P("dp", None, None, None), sspec))(params, state, x)
    ref = _bn_ref(np.asarray(x), np.asarray(scale), np.asarray(bias))
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-5)

    # running stats reflect the global batch
    np.testing.assert_allclose(np.asarray(ns["running_mean"]),
                               0.1 * np.asarray(x).mean((0, 2, 3)),
                               rtol=1e-4, atol=1e-5)


def test_syncbn_eval_uses_running_stats(dp8):
    c = 4
    bn = SyncBatchNorm(c)
    params, state = bn.init()
    state = {"running_mean": jnp.full((c,), 2.0), "running_var": jnp.full((c,), 4.0)}
    x = jnp.full((8, c, 2, 2), 4.0)

    pspec, sspec = bn.specs
    y, ns = smap(lambda p, s, x: bn.apply(p, s, x, training=False), dp8,
                 (pspec, sspec, P("dp", None, None, None)),
                 (P("dp", None, None, None), sspec))(params, state, x)
    np.testing.assert_allclose(np.asarray(y), (4.0 - 2.0) / np.sqrt(4.0 + 1e-5),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(ns["running_mean"]), 2.0)


def test_syncbn_channels_last(dp8):
    n, h, w, c = 16, 3, 3, 4
    x = jax.random.normal(jax.random.PRNGKey(3), (n, h, w, c))
    y, _, _ = smap(
        lambda x: sync_batch_norm(x, None, None, channel_axis=-1),
        dp8, (P("dp", None, None, None),), P("dp", None, None, None))(x)
    xn = np.asarray(x)
    ref = (xn - xn.mean((0, 1, 2))) / np.sqrt(xn.var((0, 1, 2)) + 1e-5)
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-5)


def test_syncbn_grads_match_full_batch(dp8):
    n, c = 16, 3
    x = jax.random.normal(jax.random.PRNGKey(4), (n, c, 2, 2))
    scale = jnp.ones((c,))
    bias = jnp.zeros((c,))

    def loss_sharded(scale, bias, x):
        y, _, _ = sync_batch_norm(x, scale, bias)
        # global mean of y² → psum over dp of local sums / N
        return jax.lax.psum(jnp.sum(y ** 2), "dp") / (n * c * 4)

    def loss_ref(scale, bias, x):
        mean = x.mean((0, 2, 3), keepdims=True)
        var = x.var((0, 2, 3), keepdims=True)
        y = (x - mean) / jnp.sqrt(var + 1e-5)
        y = y * scale[None, :, None, None] + bias[None, :, None, None]
        return jnp.mean(y ** 2)

    # check_vma=True so psum transposes efficiently (replicated cotangents);
    # grads of replicated params come out correctly reduced. Legacy
    # check_rep can't infer replication through a grad-of-psum (and with
    # the check off, the psum transpose over-counts replicated
    # cotangents by the axis size) — there, differentiate the LOCAL
    # loss piece and psum the grads instead: L = Σ_d L_d, so
    # ∇L = psum(∇L_d), the same math with correct unreplicated-cotangent
    # transposes (the numeric oracle below pins both forms).
    from apex_tpu import _compat

    def grads(scale, bias, x):
        if not _compat.LEGACY_SHARD_MAP:
            return jax.grad(loss_sharded, argnums=(0, 1))(scale, bias, x)

        def loss_local(scale, bias, x):
            y, _, _ = sync_batch_norm(x, scale, bias)
            return jnp.sum(y ** 2) / (n * c * 4)

        g = jax.grad(loss_local, argnums=(0, 1))(scale, bias, x)
        return jax.tree_util.tree_map(lambda t: jax.lax.psum(t, "dp"), g)

    g = jax.jit(jax.shard_map(
        grads, mesh=dp8,
        in_specs=(P(), P(), P("dp", None, None, None)),
        out_specs=(P(), P()),
        check_vma=not _compat.LEGACY_SHARD_MAP))(scale, bias, x)
    gref = jax.grad(loss_ref, argnums=(0, 1))(scale, bias, x)
    np.testing.assert_allclose(np.asarray(g[0]), np.asarray(gref[0]),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(g[1]), np.asarray(gref[1]),
                               rtol=1e-4, atol=1e-5)


def test_syncbn_batch_weight_ragged(dp8):
    """A zero-padded shard with batch_weight == the unpadded statistics:
    the padded elements' mean² contribution is subtracted exactly from
    the two-pass centered sum."""
    import numpy as np

    x = jax.random.normal(jax.random.PRNGKey(0), (6, 3)) + 2.0  # mean>>0
    ref_mean = jnp.mean(x, axis=0)
    ref_var = jnp.mean((x - ref_mean) ** 2, axis=0)

    xp = jnp.concatenate([x, jnp.zeros((2, 3))])  # pad to 8 rows
    y, _, _ = sync_batch_norm(
        xp, None, None, axis=None, training=True, channel_axis=-1,
        batch_weight=jnp.float32(6.0))
    # recover the (mean, var) the call used from its normalized output
    got = (xp[:6] - y[:6] * jnp.sqrt(ref_var + 1e-5))
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(jnp.broadcast_to(ref_mean,
                                                           (6, 3))),
                               rtol=1e-4, atol=1e-4)
