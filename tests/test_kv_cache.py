"""Quantized KV cache + shared-prefix reuse oracles.

Oracle pattern (SURVEY.md §4): the int8/fp8 cache vs the compute-dtype
cache with per-dtype tolerances (kernel AND XLA fallback), sharded vs
unsharded parity for the quantized path, prefix-hit vs cold-prefill
BIT-parity for greedy decode, and recompile-guard flatness across a
mixed quantized/prefix/cold workload — the capacity plays must be
invisible to everything but the byte counts.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import mesh as mx
from apex_tpu.models import gpt
from apex_tpu.serving import Request, SamplingParams
from apex_tpu.serving.engine import Admission, Engine, EngineConfig
from apex_tpu.serving.scheduler import Scheduler
from apex_tpu.transformer.testing import standalone_gpt_config

VOCAB = 96

#: decode-logits tolerance of the quantized cache vs the compute-dtype
#: cache — the quantization error band (per-row symmetric absmax)
_KV_TOL = {"int8": dict(rtol=4e-2, atol=4e-2),
           "fp8": dict(rtol=8e-2, atol=8e-2)}


def _cfg(**overrides):
    base = dict(vocab_size=VOCAB, seq_len=64)
    base.update(overrides)
    return standalone_gpt_config(**base)


def _decode_logits(cfg, params, mesh, prompt, tok, pos, n_steps=2):
    """Prefill + ``n_steps`` decode steps; returns the stacked fp32
    logits of every step (the quantization-error observable)."""
    pspecs = gpt.param_specs(cfg)

    def run(p, t, tk):
        cache, _ = gpt.prefill(cfg, p, t, max_len=cfg.seq_len)
        outs = []
        pv = pos
        cur = tk
        for _ in range(n_steps):
            lg, cache = gpt.decode_step(cfg, p, cache, cur, pv)
            outs.append(lg)
            cur = jnp.argmax(lg, axis=-1).astype(jnp.int32)
            pv = pv + 1
        return jnp.stack(outs)

    return np.asarray(jax.jit(jax.shard_map(
        run, mesh=mesh,
        in_specs=(pspecs, P(None, None), P(None)),
        out_specs=P(None, None, None), check_vma=False))(
            params, prompt, tok), np.float32)


@pytest.mark.parametrize("kind", ["int8", "fp8"])
@pytest.mark.parametrize("impl", ["xla", "kernel"])
def test_kv_quant_decode_oracle(devices8, kind, impl):
    """The quantized cache's decode logits stay inside the
    quantization error band of the compute-dtype cache over several
    chained steps — for BOTH the Pallas kernel (interpreted off-TPU)
    and the XLA fallback layout."""
    if kind == "fp8" and not hasattr(jnp, "float8_e4m3fn"):
        pytest.skip("jax build without float8_e4m3fn")
    cfg0 = _cfg(seq_len=32)
    params = gpt.init(cfg0, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0, VOCAB)
    tok = jax.random.randint(jax.random.PRNGKey(2), (2,), 0, VOCAB)
    pos = jnp.asarray([6, 3], jnp.int32)
    base = _decode_logits(cfg0, params, mesh, prompt, tok, pos)
    quant = _decode_logits(
        dataclasses.replace(cfg0, kv_cache_dtype=kind,
                            decode_attn_impl=impl),
        params, mesh, prompt, tok, pos)
    np.testing.assert_allclose(quant, base, **_KV_TOL[kind])


def _run_trace(eng, reqs, **kw):
    sched = Scheduler(eng, **kw)
    for r in reqs:
        sched.submit(r)
    sched.run_until_idle()
    return sched


def _mixed_requests(n, max_prompt_len, *, seed0, eos=None, prefix=None):
    """Greedy + sampled lanes; with ``prefix``, every other prompt
    starts with it (the shared-template workload)."""
    reqs = []
    for i in range(n):
        p_len = 1 + (7 * i + 3) % max_prompt_len
        tail = [int(t) for t in jax.random.randint(
            jax.random.PRNGKey(seed0 + i), (p_len,), 0, VOCAB)]
        prompt = tail
        if prefix is not None and i % 2 == 0:
            prompt = (list(prefix) + tail)[:max_prompt_len]
            if len(prompt) <= len(prefix):
                prompt = list(prefix[:max_prompt_len - 1]) + tail[:1]
        sp = (SamplingParams(temperature=0.8 + 0.1 * (i % 3),
                             top_k=(0, 5, 9)[i % 3], seed=seed0 + i)
              if i % 3 == 1 else SamplingParams())
        reqs.append(Request(f"kv{seed0}_{i}", prompt,
                            max_tokens=3 + i % 4, sampling=sp,
                            eos_token_id=eos))
    return reqs


@pytest.mark.slow  # plain tp2-vs-tp1 engine parity stays tier-1 (test_serving); the quantized composition is long-suite (fleet-router tier-1 offset)
def test_quantized_engine_tp2_matches_tp1(devices8):
    """Sharded-vs-unsharded parity for the quantized serving path (the
    repo-wide oracle pattern): the same trace over tp=2 — per-head
    scales shard with their heads — emits identical tokens."""
    cfg = _cfg(kv_cache_dtype="int8")
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    ecfg = EngineConfig(slots=2, max_prompt_len=8, max_seq_len=20)
    reqs = _mixed_requests(3, 8, seed0=300)
    clone = lambda: [Request(r.request_id, r.prompt, r.max_tokens,
                             sampling=r.sampling) for r in reqs]
    got1 = {rid: c.tokens for rid, c in _run_trace(
        Engine(cfg, params, mx.build_mesh(tp=1, devices=devices8[:1]),
               ecfg), clone()).completions.items()}
    got2 = {rid: c.tokens for rid, c in _run_trace(
        Engine(cfg, params, mx.build_mesh(tp=2, devices=devices8[:2]),
               ecfg), clone()).completions.items()}
    assert got1 == got2


def test_cache_bytes_reduction_and_accessor(devices8):
    """The capacity headline: int8 storage shrinks cache bytes per
    slot >= 1.9x vs the compute-dtype cache (data plane / storage
    width, plus the fp32 scale plane at 1/head_dim overhead), and
    ``Engine.cache_bytes()`` reports exactly the device buffer
    bytes."""
    params_of = {}
    engines = {}
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    ecfg = EngineConfig(slots=2, max_prompt_len=8, max_seq_len=16)
    for kind in ("auto", "int8", "fp8"):
        if kind == "fp8" and not hasattr(jnp, "float8_e4m3fn"):
            continue
        cfg = _cfg(kv_cache_dtype=kind)
        params_of[kind] = gpt.init(cfg, jax.random.PRNGKey(0))
        engines[kind] = Engine(cfg, params_of[kind], mesh, ecfg)
    base = engines["auto"].cache_bytes()
    # exact accounting: [l, 2, B, h, S, d] data + [l, 2, B, h, S] scale
    cfg = _cfg()
    l, h, d = cfg.num_layers, cfg.num_heads, cfg.head_dim
    n = l * 2 * ecfg.slots * h * ecfg.max_seq_len
    assert base == n * d * jnp.dtype(cfg.compute_dtype).itemsize
    for kind in engines:
        if kind == "auto":
            continue
        got = engines[kind].cache_bytes()
        assert got == n * d * 1 + n * 4  # storage byte + fp32 scale
        ratio = base / got
        assert ratio >= 1.9, (
            f"{kind} cache-bytes reduction {ratio:.2f}x < 1.9x")
    # summary() carries the accessor
    s = Scheduler(engines["int8"]).summary()
    assert s["cache_bytes"] == engines["int8"].cache_bytes()


@pytest.mark.parametrize("kv", [
    "auto",
    # the quantized prefix hit rides the identical pooled-copy +
    # tail-extend path with only the slot-insert quantize added (the
    # quantized write contract has its own tier-1 oracle) — long-suite
    # confirmation (tier-1 budget offset for the fleet-router suite)
    pytest.param("int8", marks=pytest.mark.slow)])
def test_prefix_hit_matches_cold(devices8, kv):
    """The prefix-reuse bit-parity oracle: a prompt admitted through a
    pooled prefix (compiled gather copy + tail-only prefill) emits
    EXACTLY the cold-prefill stream — greedy and seeded-sampled lanes,
    plain and quantized caches."""
    cfg = _cfg(kv_cache_dtype=kv)
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    ecfg = EngineConfig(slots=2, max_prompt_len=10, max_seq_len=24,
                        prefix_pool_slots=1)
    template = [int(t) for t in jax.random.randint(
        jax.random.PRNGKey(77), (9,), 0, VOCAB)]
    eng = Engine(cfg, params, mesh, ecfg).warmup()  # apex: noqa[TIER1-COST]: tiny engine; prefix-hit vs cold parity needs all warmed variants
    assert eng.prefix_splits == (8,)
    eng.register_prefix(template)
    cold = Engine(cfg, params, mesh, dataclasses.replace(
        ecfg, prefix_pool_slots=0)).warmup()  # apex: noqa[TIER1-COST]: cold-side twin of the parity oracle; same tiny engine
    for i, sp in enumerate((dict(), dict(temperature=0.9, top_k=5,
                                         seed=41))):
        prompt = template[:8] + [3 + i, 5]
        hit = eng.match_prefix(prompt)
        assert hit == (0, 8)
        out = {}
        for name, e in (("hit", eng), ("cold", cold)):
            kw = dict(sp)
            page, ps = (hit if name == "hit" else (None, 0))
            res = e.admit_many([Admission(
                slot=0, prompt=prompt, max_tokens=4,
                prefix_page=page, prefix_len=ps, **kw)])[0]
            toks = [res.first_token]
            for _ in range(3):
                t, _, _ = e.step()
                toks.append(int(t[0, 0]))
            out[name] = toks
        assert out["hit"] == out["cold"], (
            f"prefix-hit drift ({'sampled' if sp else 'greedy'}): "
            f"{out}")
    # the hit paid the TAIL bucket, not the full prompt bucket
    res = eng.admit_many([Admission(
        slot=1, prompt=template[:8] + [9, 9], max_tokens=2,
        prefix_page=0, prefix_len=8)])[0]
    assert res.bucket == 8 and res.batch_size == 1


@pytest.mark.slow  # register/match/admission stay exercised in tier-1 by the hit-parity oracle; the contract corners here are long-suite (fleet-router tier-1 offset)
def test_prefix_registration_and_match(devices8):
    """Host-side pool semantics: dedupe, longest-split matching,
    page/split validation, pool-full and too-short errors, and
    match_prefix returning None for misses / tail-less prompts."""
    cfg = _cfg()
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    ecfg = EngineConfig(slots=2, max_prompt_len=10, max_seq_len=24,
                        prefix_pool_slots=1)
    eng = Engine(cfg, params, mesh, ecfg).warmup()
    template = list(range(1, 10))  # 9 tokens -> stored at split 8
    page = eng.register_prefix(template)
    assert page == 0
    assert eng.register_prefix(template) == 0  # dedupe, no new page
    assert eng.register_prefix(template[:8]) == 0  # same stored slice
    with pytest.raises(ValueError, match="full"):
        eng.register_prefix(list(range(20, 29)))
    with pytest.raises(ValueError, match="shorter"):
        eng.register_prefix([1, 2, 3])
    with pytest.raises(ValueError, match="vocab"):
        eng.register_prefix([VOCAB] * 8)
    # matching: longest usable split, >= 1 tail token required
    assert eng.match_prefix(template[:8] + [50]) == (0, 8)
    assert eng.match_prefix(template[:8]) is None       # no tail
    assert eng.match_prefix([9] + template[:7]) is None  # mismatch
    # admission-side validation: mismatched prompt vs page is loud
    with pytest.raises(ValueError, match="does not match"):
        eng.admit_many([Admission(slot=0, prompt=[9] * 9, max_tokens=2,
                                  prefix_page=0, prefix_len=8)])
    with pytest.raises(ValueError, match="prefix_len"):
        eng.admit_many([Admission(slot=0, prompt=template[:8] + [1],
                                  max_tokens=2, prefix_page=0,
                                  prefix_len=7)])
    with pytest.raises(ValueError, match="without prefix_page"):
        eng.admit_many([Admission(slot=0, prompt=template[:8] + [1],
                                  max_tokens=2, prefix_len=8)])
    # pool disabled: config knob off means no pool API
    cold = Engine(cfg, params, mesh,
                  dataclasses.replace(ecfg, prefix_pool_slots=0))
    assert not cold.prefix_pool_enabled
    assert cold.match_prefix(template) is None
    with pytest.raises(ValueError, match="disabled"):
        cold.register_prefix(template)
    # a ladder with no usable split is rejected at construction
    with pytest.raises(ValueError, match="usable split"):
        Engine(cfg, params, mesh, EngineConfig(
            slots=2, max_prompt_len=8, max_seq_len=12,
            prompt_buckets=(8,), prefix_pool_slots=1))
    # registering before warmup is loud (warmup resets the pool and
    # would silently drop the template otherwise)
    fresh = Engine(cfg, params, mesh, ecfg)
    fresh.register_prefix(template)
    with pytest.raises(ValueError, match="before warmup"):
        fresh.warmup()


def test_prefill_extend_matches_cold_compute_scores(devices8):
    """attn_score_dtype="compute" parity: prefill_extend shares THE
    materialised-scores expression with the cold path
    (gpt._xla_attn_probs), so the end logits and tail K/V are
    bit-identical to a cold prefill_many under BOTH score-dtype
    branches."""
    for sd in ("f32", "compute"):
        cfg = _cfg(seq_len=32, attn_score_dtype=sd)
        params = gpt.init(cfg, jax.random.PRNGKey(0))
        mesh = mx.build_mesh(tp=1, devices=devices8[:1])
        pspecs = gpt.param_specs(cfg)
        toks = jax.random.randint(jax.random.PRNGKey(11), (1, 10), 0,
                                  VOCAB)

        def run(p, t):
            cold_cache, cold_lg = gpt.prefill_many(
                cfg, p, t, jnp.asarray([9], jnp.int32), max_len=10)
            pre_cache, _ = gpt.prefill_many(
                cfg, p, t[:, :8], jnp.asarray([7], jnp.int32),
                max_len=8)
            tail = jnp.concatenate(
                [t[:, 8:], jnp.zeros((1, 6), jnp.int32)], axis=1)
            tail_kv, hit_lg = gpt.prefill_extend(
                cfg, p, pre_cache, tail, jnp.asarray([1], jnp.int32),
                prefix_len=8)
            return cold_cache, cold_lg, tail_kv, hit_lg

        cold_cache, cold_lg, tail_kv, hit_lg = jax.jit(jax.shard_map(
            run, mesh=mesh, in_specs=(pspecs, P(None, None)),
            out_specs=(P(None, None, None, "tp", None, None),
                       P(None, None),
                       P(None, None, None, "tp", None, None),
                       P(None, None)), check_vma=False))(params, toks)
        np.testing.assert_array_equal(
            np.asarray(hit_lg), np.asarray(cold_lg), err_msg=sd)
        np.testing.assert_array_equal(
            np.asarray(tail_kv[:, :, :, :, :2], np.float32),
            np.asarray(cold_cache[:, :, :, :, 8:10], np.float32),
            err_msg=sd)


def test_prefix_pool_rejects_moe(devices8):
    """MoE expert capacity depends on the routed token count, so
    tail-only routing breaks hit/cold parity — rejected loudly at
    engine construction AND at the gpt level."""
    cfg = _cfg(num_experts=2)
    params = gpt.init(_cfg(), jax.random.PRNGKey(0))  # never touched
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    with pytest.raises(ValueError, match="num_experts"):
        Engine(cfg, params, mesh, EngineConfig(
            slots=2, max_prompt_len=10, max_seq_len=24,
            prefix_pool_slots=1))
    with pytest.raises(ValueError, match="num_experts"):
        gpt.prefill_extend(cfg, params, None,
                           np.zeros((1, 8), np.int32),
                           np.zeros((1,), np.int32), prefix_len=8)


# register/match/admission stay tier-1 via the hit-parity oracle
# (test_prefix_hit_matches_cold); the pool-reset failure corner is
# long-suite (durable-journal tier-1 offset)
@pytest.mark.slow
def test_register_prefix_failure_resets_pool(devices8):
    """The pool insert DONATES the pool buffer: a failing registration
    must reset the pool + registry to a clean empty state (no index
    entries pointing into a dead buffer, no leaked page) and leave the
    engine registerable again."""
    cfg = _cfg()
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    eng = Engine(cfg, params, mesh, EngineConfig(
        slots=2, max_prompt_len=10, max_seq_len=24,
        prefix_pool_slots=2)).warmup()
    t1 = list(range(1, 10))
    assert eng.register_prefix(t1) == 0

    def boom(*a, **kw):
        raise RuntimeError("injected pool-insert failure")

    real = eng._pool_inserts
    eng._pool_inserts = {pb: boom for pb in real}
    with pytest.raises(RuntimeError, match="injected"):
        eng.register_prefix(list(range(20, 29)))
    eng._pool_inserts = real
    # clean slate: registry empty, no stale match, page 0 free again
    assert eng._prefix_used == 0
    assert eng.match_prefix(t1 + [5]) is None
    assert eng.register_prefix(t1) == 0
    hit = eng.match_prefix(t1[:8] + [3])
    assert hit == (0, 8)
    res = eng.admit_many([Admission(slot=0, prompt=t1[:8] + [3],
                                    max_tokens=2, prefix_page=hit[0],
                                    prefix_len=hit[1])])[0]
    assert 0 <= res.first_token < VOCAB


@pytest.mark.slow  # the hit==cold BIT-parity oracle stays tier-1 per dtype; this two-engine scheduler/telemetry composition is long-suite (multi-tenant tier-1 offset)
def test_scheduler_prefix_detection_and_oracle(devices8):
    """End-to-end through the scheduler: hits are detected at submit
    (hash-keyed, transparent to callers), counted in telemetry and
    summary(), and the mixed hit/miss trace emits token streams
    identical to the SAME trace on a pool-less engine."""
    from apex_tpu.telemetry import Registry

    cfg = _cfg()
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    ecfg = EngineConfig(slots=2, max_prompt_len=10, max_seq_len=24,
                        decode_chunk=2, prefix_pool_slots=1)
    template = [int(t) for t in jax.random.randint(
        jax.random.PRNGKey(88), (8,), 0, VOCAB)]
    reqs = _mixed_requests(5, 10, seed0=500, prefix=template)
    clone = lambda: [Request(r.request_id, r.prompt, r.max_tokens,
                             sampling=r.sampling) for r in reqs]
    registry = Registry()
    eng = Engine(cfg, params, mesh, ecfg).warmup()
    eng.register_prefix(template)
    sched = _run_trace(eng, clone(), registry=registry,
                       pipeline_depth=2)
    s = sched.summary()
    n_hits = sum(1 for r in reqs
                 if eng.match_prefix(list(r.prompt)) is not None)
    assert n_hits >= 2  # the trace actually exercises the hit path
    assert s["prefix_hits"] == n_hits
    assert s["prefix_misses"] == len(reqs) - n_hits
    assert registry.counter("serving_prefix_hits_total").value == n_hits
    assert registry.gauge("serving_kv_cache_bytes").value == \
        eng.cache_bytes()
    cold = _run_trace(
        Engine(cfg, params, mesh, dataclasses.replace(
            ecfg, prefix_pool_slots=0)).warmup(), clone(),
        pipeline_depth=2)
    assert {rid: c.tokens for rid, c in sched.completions.items()} == \
        {rid: c.tokens for rid, c in cold.completions.items()}
    assert cold.summary()["prefix_hits"] == 0.0


@pytest.mark.slow  # guard flatness (test_resilience/test_serving), int8 parity, and prefix hit-parity each stay tier-1; this quantized+prefix+guard composition is long-suite (slo-observatory tier-1 offset)
def test_quantized_prefix_guard_stays_flat(devices8):
    """The PR-4 acceptance test extended to the capacity plays: a
    quantized (int8) engine with a prefix pool — warmup, register, then
    a mixed workload of prefix hits, cold admissions in BOTH buckets,
    chunked decode, varied sampling — never compiles inside an armed
    RecompileGuard."""
    from apex_tpu.telemetry.recompile import RecompileError

    cfg = _cfg(kv_cache_dtype="int8")
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    eng = Engine(cfg, params, mesh, EngineConfig(
        slots=2, max_prompt_len=10, max_seq_len=24, decode_chunk=4,
        prefix_pool_slots=1))
    try:
        eng.warmup()
        sizes0 = eng.compiled_cache_sizes()
        assert set(sizes0.values()) == {1}, sizes0
        for name in ("pool_init", "pool_p8", "admit_prefix_p8_t8"):
            assert name in sizes0, sorted(sizes0)
        template = [int(t) for t in jax.random.randint(
            jax.random.PRNGKey(99), (8,), 0, VOCAB)]
        # build requests OUTSIDE the guard (prompt synthesis compiles)
        reqs = _mixed_requests(5, 10, seed0=700, prefix=template)
        with eng.recompile_guard() as g:
            eng.register_prefix(template)  # rides compiled pool_p8
            sched = Scheduler(eng, pipeline_depth=2)
            for r in reqs:
                sched.submit(r)
            sched.run_until_idle()
            assert len(sched.completions) == 5
            assert sched.summary()["prefix_hits"] >= 2
            assert g.check() == {}
        assert not g.tripped
        assert eng.compiled_cache_sizes() == sizes0
        sent = eng.recompile_sentinel()
        if sent.monitoring_available:
            with pytest.raises(RecompileError):
                with eng.recompile_guard():
                    jax.jit(lambda x: x * 3.0)(np.arange(5.0))
    finally:
        eng.close()


def test_decode_attn_impl_predicate(monkeypatch):
    """THE decode-attention gate, arm by arm (satellite: one
    documented predicate, unit-tested, shared by the quantized
    layout). On-TPU behaviour is simulated by patching
    ``use_interpret``."""
    import apex_tpu.kernels._utils as ku

    base = standalone_gpt_config()
    # off-TPU (interpret): always xla, any horizon or dtype
    monkeypatch.setattr(ku, "use_interpret", lambda: True)
    assert gpt._decode_attn_impl(base, 4096) == "xla"
    assert gpt._decode_attn_impl(
        dataclasses.replace(base, kv_cache_dtype="int8"), 4096) == "xla"
    # on-TPU: kernel from horizon 128, xla below
    monkeypatch.setattr(ku, "use_interpret", lambda: False)
    assert gpt._decode_attn_impl(base, 128) == "kernel"
    assert gpt._decode_attn_impl(base, 127) == "xla"
    # f16 compute pins an UNQUANTIZED cache to xla (the widen-both-
    # caches trap) but a quantized cache crosses in storage dtype
    f16 = dataclasses.replace(base, compute_dtype=jnp.float16)
    assert gpt._decode_attn_impl(f16, 4096) == "xla"
    assert gpt._decode_attn_impl(
        dataclasses.replace(f16, kv_cache_dtype="int8"),
        4096) == "kernel"
    # explicit settings pass through; junk is loud
    assert gpt._decode_attn_impl(
        dataclasses.replace(base, decode_attn_impl="xla"), 4096) == "xla"
    assert gpt._decode_attn_impl(
        dataclasses.replace(base, decode_attn_impl="kernel"), 8) == \
        "kernel"
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        gpt._kv_cache_dtype(
            dataclasses.replace(base, kv_cache_dtype="int4"))
