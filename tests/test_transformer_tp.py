"""Tensor-parallel core tests.

Parity model: apex tests/L0/run_transformer/{test_parallel_state,
test_mapping, test_layers, test_cross_entropy, test_random,
test_microbatches}.py (U), rebuilt on the CPU-simulated 8-device mesh.
Oracle: unsharded jax.numpy reference at fp32.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import mesh as mx
from apex_tpu.transformer import parallel_state as ps
from apex_tpu.transformer import microbatches as mb
from apex_tpu.transformer.tensor_parallel import (
    cross_entropy as ce,
    layers as tpl,
    mappings as mp,
    random as tpr,
)


@pytest.fixture()
def tp4(devices8):
    m = mx.build_mesh(tp=4, devices=devices8[:4])
    yield m


def smap(f, mesh, in_specs, out_specs):
    return jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_vma=False)
    )


# -- parallel_state --------------------------------------------------------
def test_parallel_state_sizes(devices8):
    st = ps.initialize_model_parallel(2, 2, devices=devices8)
    assert ps.model_parallel_is_initialized()
    assert ps.get_tensor_model_parallel_world_size() == 2
    assert ps.get_pipeline_model_parallel_world_size() == 2
    assert ps.get_data_parallel_world_size() == 2
    assert st.world_size == 8
    ps.destroy_model_parallel()
    assert not ps.model_parallel_is_initialized()
    with pytest.raises(RuntimeError):
        ps.get_mesh()


def test_parallel_state_vpp_requires_pp(devices8):
    with pytest.raises(ValueError):
        ps.initialize_model_parallel(1, 1, 2, devices=devices8)


# -- mappings: forward + backward semantics --------------------------------
def test_copy_and_reduce_mappings(tp4):
    x = jnp.ones((2, 3))

    def f(x):
        # copy: identity fwd; grads of a per-rank-weighted sum must be
        # all-reduced, i.e. sum of rank weights everywhere.
        r = mp.lax.axis_index("tp").astype(jnp.float32)
        loss = jnp.sum(mp.copy_to_tensor_model_parallel_region(x) * (r + 1.0))
        return loss

    def g_of(x):
        # per-rank loss summed → total = sum_r (r+1) * sum(x); dx = 10
        return jax.grad(f)(x)

    # concatenate per-rank grads along dim 0: every rank must hold 10s
    g = smap(g_of, tp4, P(), P("tp", None))(x)
    g = np.asarray(g).reshape(4, 2, 3)
    np.testing.assert_allclose(g, 10.0 * np.ones((4, 2, 3)))

    def h(x):
        r = mp.lax.axis_index("tp").astype(jnp.float32)
        y = mp.reduce_from_tensor_model_parallel_region(x * (r + 1.0))
        return y

    y = smap(h, tp4, P(), P("tp", None))(x)
    y = np.asarray(y).reshape(4, 2, 3)
    np.testing.assert_allclose(y[0], 10.0 * np.ones((2, 3)))
    # reduce bwd = identity: each rank's grad is just upstream grad
    def h2(x):
        return jnp.sum(mp.reduce_from_tensor_model_parallel_region(x))

    g2 = smap(jax.grad(h2), tp4, P(), P("tp", None))(x)
    np.testing.assert_allclose(np.asarray(g2).reshape(4, 2, 3)[1], 1.0)


def test_scatter_gather_roundtrip_and_grads(tp4):
    x = jnp.arange(2 * 8, dtype=jnp.float32).reshape(2, 8)

    def f(x):
        local = mp.scatter_to_tensor_model_parallel_region(x)  # [2, 2]
        return mp.gather_from_tensor_model_parallel_region(local)

    y = smap(f, tp4, P(), P())(x)
    np.testing.assert_allclose(y, x)

    # grad of sum through scatter→gather is ones (each element used once)
    g = smap(jax.grad(lambda x: jnp.sum(f(x))), tp4, P(), P())(x)
    np.testing.assert_allclose(g, np.ones_like(x))


def test_sequence_parallel_mappings(tp4):
    x = jnp.arange(8 * 3, dtype=jnp.float32).reshape(8, 3)  # [s, h]

    def f(xs):
        full = mp.gather_from_sequence_parallel_region(xs, "tp", True)
        return mp.reduce_scatter_to_sequence_parallel_region(full, "tp")

    # input sharded on seq dim; reduce-scatter of 4 identical gathers = 4x
    y = smap(f, tp4, P("tp", None), P("tp", None))(x)
    np.testing.assert_allclose(y, 4.0 * x)

    def g(xs):
        return jnp.sum(mp.scatter_to_sequence_parallel_region(xs, "tp") ** 2)

    # scatter from replicated: grads all-gathered back to full shape
    grad = smap(jax.grad(g), tp4, P(), P())(x)
    np.testing.assert_allclose(grad, 2.0 * x)


# -- layers vs unsharded reference -----------------------------------------
def _ref_linear(x, k, b):
    return x @ k + b


def test_column_parallel_matches_dense(tp4):
    key = jax.random.PRNGKey(0)
    lyr = tpl.ColumnParallelLinear(6, 8, gather_output=True)
    params = lyr.init(key)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 6))

    y = smap(
        lambda p, x: lyr.apply(p, x), tp4, (lyr.specs, P()), P()
    )(params, x)
    ref = _ref_linear(x, params["kernel"], params["bias"])
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5, atol=1e-5)

    # gradient parity vs dense reference
    def loss_sharded(p, x):
        return jnp.sum(lyr.apply(p, x) ** 2)

    def loss_ref(p, x):
        return jnp.sum(_ref_linear(x, p["kernel"], p["bias"]) ** 2)

    g = smap(jax.grad(loss_sharded), tp4, (lyr.specs, P()), lyr.specs)(params, x)
    gref = jax.grad(loss_ref)(params, x)
    np.testing.assert_allclose(np.asarray(g["kernel"]), np.asarray(gref["kernel"]),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(g["bias"]), np.asarray(gref["bias"]),
                               rtol=1e-4, atol=1e-4)


def test_row_parallel_matches_dense(tp4):
    key = jax.random.PRNGKey(2)
    lyr = tpl.RowParallelLinear(8, 6, input_is_parallel=False)
    params = lyr.init(key)
    x = jax.random.normal(jax.random.PRNGKey(3), (4, 8))

    y = smap(lambda p, x: lyr.apply(p, x), tp4, (lyr.specs, P()), P())(params, x)
    ref = _ref_linear(x, params["kernel"], params["bias"])
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_column_row_sequence_parallel_pair(tp4):
    """SP sandwich: seq-sharded in → Column(SP) → Row(SP) → seq-sharded out
    equals the dense computation (apex test_layers.py SP cases (U))."""
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(4), 3)
    col = tpl.ColumnParallelLinear(6, 12, sequence_parallel=True)
    row = tpl.RowParallelLinear(12, 6, sequence_parallel=True)
    pc, pr = col.init(k1), row.init(k2)
    x = jax.random.normal(k3, (8, 2, 6))  # [s, b, h]

    def f(pc, pr, xs):
        h = col.apply(pc, xs)
        h = jax.nn.gelu(h)
        return row.apply(pr, h)

    y = smap(f, tp4, (col.specs, row.specs, P("tp", None, None)),
             P("tp", None, None))(pc, pr, x)
    ref = _ref_linear(jax.nn.gelu(_ref_linear(x, pc["kernel"], pc["bias"])),
                      pr["kernel"], pr["bias"])
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-4, atol=1e-4)


def test_vocab_parallel_embedding(tp4):
    emb = tpl.VocabParallelEmbedding(16, 8)
    params = emb.init(jax.random.PRNGKey(5))
    ids = jnp.array([[0, 3, 7, 15], [8, 9, 1, 2]])

    y = smap(lambda p, i: emb.apply(p, i), tp4, (emb.specs, P()), P())(params, ids)
    ref = jnp.take(params["table"], ids, axis=0)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5, atol=1e-5)

    # grad wrt table matches dense embedding grad
    def loss(p, i):
        return jnp.sum(emb.apply(p, i) ** 2)

    g = smap(jax.grad(loss), tp4, (emb.specs, P()), emb.specs)(params, ids)
    gref = jax.grad(lambda p, i: jnp.sum(jnp.take(p["table"], i, 0) ** 2))(params, ids)
    np.testing.assert_allclose(np.asarray(g["table"]), np.asarray(gref["table"]),
                               rtol=1e-4, atol=1e-4)


# -- vocab-parallel cross entropy ------------------------------------------
@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_vocab_parallel_cross_entropy(tp4, smoothing):
    s, b, v = 4, 2, 16
    logits = jax.random.normal(jax.random.PRNGKey(6), (s, b, v)) * 3.0
    target = jax.random.randint(jax.random.PRNGKey(7), (s, b), 0, v)

    def sharded(logits, target):
        return ce.vocab_parallel_cross_entropy(logits, target, smoothing)

    loss = smap(sharded, tp4, (P(None, None, "tp"), P()), P())(logits, target)

    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, target[..., None], axis=-1)[..., 0]
    ref = (1 - smoothing) * nll - smoothing * jnp.mean(logp, axis=-1)
    np.testing.assert_allclose(np.asarray(loss), np.asarray(ref), rtol=1e-5, atol=1e-5)

    # gradient parity
    def sum_sharded(logits, target):
        return jnp.sum(ce.vocab_parallel_cross_entropy(logits, target, smoothing))

    g = smap(jax.grad(sum_sharded), tp4, (P(None, None, "tp"), P()),
             P(None, None, "tp"))(logits, target)

    def sum_ref(logits):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, target[..., None], axis=-1)[..., 0]
        return jnp.sum((1 - smoothing) * nll - smoothing * jnp.mean(logp, axis=-1))

    gref = jax.grad(sum_ref)(logits)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gref), rtol=1e-4, atol=1e-5)


# -- RNG tracker -----------------------------------------------------------
def test_model_parallel_rng_distinct_per_rank(tp4):
    key = jax.random.PRNGKey(8)

    def f(_):
        k = tpr.model_parallel_rng_key(key)
        return jax.random.uniform(k, (4,))

    outs = smap(f, tp4, P("tp"), P("tp"))(jnp.zeros((4,)))
    outs = np.asarray(outs).reshape(4, 4)
    # every rank draws a different stream
    for i in range(4):
        for j in range(i + 1, 4):
            assert not np.allclose(outs[i], outs[j])


def test_rng_tracker_fork_is_functional():
    tr = tpr.RNGStatesTracker().add("a", 0)
    k1, tr2 = tr.fork("a")
    k2, _ = tr.fork("a")  # same source state → same key (pure)
    assert np.array_equal(np.asarray(k1), np.asarray(k2))
    k3, _ = tr2.fork("a")
    assert not np.array_equal(np.asarray(k1), np.asarray(k3))
    with pytest.raises(ValueError):
        tr.add("a", 1)
    with pytest.raises(ValueError):
        tr.fork("missing")
    leaves, treedef = jax.tree.flatten(tr2)
    assert jax.tree.unflatten(treedef, leaves).get_states().keys() == {"a"}


def test_checkpoint_matches_uncheckpointed():
    def block(x):
        return jnp.sum(jnp.tanh(x) ** 2)

    x = jax.random.normal(jax.random.PRNGKey(9), (8,))
    g1 = jax.grad(block)(x)
    g2 = jax.grad(tpr.checkpoint(block))(x)
    g3 = jax.grad(lambda x: tpr.checkpoint_call(block, x))(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g3), rtol=1e-6)


# -- microbatches ----------------------------------------------------------
def test_constant_microbatches():
    c = mb.ConstantNumMicroBatches(64, 4, 2)
    assert c.get() == 8
    c.update(10_000, True)
    assert c.get() == 8
    with pytest.raises(ValueError):
        mb.ConstantNumMicroBatches(65, 4, 2)


def test_rampup_microbatches():
    r = mb.build_num_microbatches_calculator((16, 16, 96), 64, 4, 2)
    assert r.get_current_global_batch_size() == 16
    assert r.get() == 2
    r.update(48, False)
    assert r.get_current_global_batch_size() == 32
    r.update(1_000, False)
    assert r.get_current_global_batch_size() == 64
    assert r.get() == 8
