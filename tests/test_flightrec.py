"""apex_tpu.telemetry.flightrec + replay — the serving black box.

Headline oracle: a seeded chaos soak auto-dumps a post-mortem bundle
on its first fault, and ``python -m apex_tpu.telemetry.replay``
rebuilds the whole run from that bundle and reproduces every
interrupted request's emitted stream BIT-identically — with the fault
plan re-armed AND replaying clean (per-request determinism means the
streams cannot depend on where faults land). The ``--report`` path is
pinned stdlib-only in a jax/numpy-purged subprocess, the recorder ring
is pinned on wraparound/drop accounting, bundles are pinned atomic +
immutable, the ``/debug`` endpoints are scraped live (and pinned
absent without a recorder), the recorder keeps an armed recompile
guard flat, and ``Engine.close()`` is pinned idempotent/re-entrant
(the double-release regression)."""

import json
import os
import subprocess
import sys
import urllib.error
import urllib.request

import jax
import pytest

from apex_tpu import mesh as mx
from apex_tpu.models import gpt
from apex_tpu.serving import Request, SamplingParams
from apex_tpu.serving.engine import Engine, EngineConfig
from apex_tpu.serving.resilience import FaultPlan, ResilienceConfig
from apex_tpu.serving.scheduler import Scheduler
from apex_tpu.telemetry import MetricsServer, Registry
from apex_tpu.telemetry.flightrec import (
    EVENT_FIELDS,
    FlightRecorder,
    read_bundle,
    write_bundle,
)
from apex_tpu.telemetry.replay import render_report, replay_bundle
from apex_tpu.transformer.testing import standalone_gpt_config

VOCAB = 96


@pytest.fixture(scope="module")
def model(devices8):
    cfg = standalone_gpt_config(vocab_size=VOCAB, seq_len=64)
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    return cfg, params, mesh


def _reqs(n, *, seed0=9000, max_tokens=5):
    out = []
    for i in range(n):
        p_len = 2 + (3 * i) % 6
        prompt = [int(t) for t in jax.random.randint(
            jax.random.PRNGKey(seed0 + i), (p_len,), 0, VOCAB)]
        sp = (SamplingParams(temperature=0.9, top_k=7, seed=seed0 + i)
              if i % 2 else SamplingParams())
        out.append(Request(f"b{i}", prompt, max_tokens=max_tokens,
                           sampling=sp))
    return out


@pytest.fixture(scope="module")
def chaos_bundle(model, tmp_path_factory):
    """ONE seeded chaos soak shared by the round-trip tests: a
    FaultPlan.random soak whose first fault auto-dumps a bundle
    mid-flight (interrupted requests recorded with partial emitted
    prefixes), plus the engine/scheduler that produced it."""
    cfg, params, mesh = model
    # seed chosen so the seeded plan fires error/nan faults inside this
    # short trace (pinned below — a plan that never fires would turn
    # the round-trip test into a no-op)
    plan = FaultPlan.random(5, 3, max_index=8, slots=2)
    eng = Engine(cfg, params, mesh,
                 EngineConfig(slots=2, max_prompt_len=8, max_seq_len=24,
                              decode_chunk=2), fault_plan=plan)
    rec = FlightRecorder()
    bundle_dir = str(tmp_path_factory.mktemp("bundles"))
    sched = Scheduler(
        eng, pipeline_depth=2, recorder=rec, bundle_dir=bundle_dir,
        bundle_meta={"params": {"init_seed": 0}},
        resilience=ResilienceConfig(backoff_base_s=0.001, max_retries=4))
    reqs = _reqs(8)
    for r in reqs:
        sched.submit(r)
    sched.run_until_idle()
    assert [s for s in plan.injected if s.kind in ("error", "nan")], \
        "seed produced no hard fault — pick another seed"
    assert sched.bundles_written, "no auto-dumped bundle"
    yield sched.bundles_written[0], eng, sched, rec, reqs
    # the guard-flat test arms a recompile guard on this engine, which
    # installs its sentinel: close at module teardown so the listener
    # never leaks into later modules (the engines-in-a-loop footgun)
    eng.close()


# --- recorder unit coverage (host-only, fast) -------------------------------


def test_ring_wraparound_and_drop_accounting():
    clock_t = [0.0]

    def clock():
        clock_t[0] += 1.0
        return clock_t[0]

    rec = FlightRecorder(capacity=8, clock=clock)
    for i in range(20):
        rec.record("finish", f"r{i}", "length", i)
    evs = rec.events()
    assert len(evs) == 8
    # wraparound keeps the NEWEST events, seq stays monotonic with no
    # reordering across the wrap
    assert [e[0] for e in evs] == list(range(13, 21))
    assert rec.seq == 20
    s = rec.summary()
    assert s["events_total"] == 20 and s["events_dropped"] == 12
    assert s["events"] == 8 and s["last_seq"] == 20
    # tail(n) returns the n newest as dicts with NAMED fields
    tail = rec.tail(3)
    assert [t["seq"] for t in tail] == [18, 19, 20]
    assert tail[-1] == {"seq": 20, "t": 20.0, "event": "finish",
                        "request_id": "r19", "reason": "length",
                        "n_tokens": 19}
    # unknown names survive as raw args (a post-mortem never loses
    # data to a rename)
    rec.record("not_a_known_event", 1, 2)
    assert rec.tail(1)[0]["args"] == [1, 2]
    rec.clear()
    assert rec.seq == 0 and rec.summary()["events_total"] == 0


def test_bundle_write_atomic_and_immutable(tmp_path):
    path = str(tmp_path / "b0")
    out = write_bundle(path, {
        "manifest.json": {"cause": "t", "n": 1},
        "events.jsonl": [{"seq": 1}, {"seq": 2}],
    })
    assert out == path
    # no temp droppings next to the bundle
    assert sorted(p.name for p in tmp_path.iterdir()) == ["b0"]
    back = read_bundle(path)
    assert back["manifest.json"]["cause"] == "t"
    assert back["events.jsonl"] == [{"seq": 1}, {"seq": 2}]
    # bundles are immutable evidence
    with pytest.raises(FileExistsError):
        write_bundle(path, {"manifest.json": {}})
    # a directory that is not a bundle is a clear error
    os.makedirs(str(tmp_path / "junk"))
    with pytest.raises(ValueError, match="manifest"):
        read_bundle(str(tmp_path / "junk"))
    with pytest.raises(FileNotFoundError):
        read_bundle(str(tmp_path / "missing"))


# --- the chaos round trip ---------------------------------------------------


def test_chaos_bundle_contents_and_decision_log(chaos_bundle):
    bundle_path, eng, sched, rec, reqs = chaos_bundle
    b = read_bundle(bundle_path)
    man = b["manifest.json"]
    assert man["cause"].startswith("fault-")
    assert man["meta"] == {"params": {"init_seed": 0}}
    assert man["flightrec"]["events_total"] > 0
    # every recorded event name is in the vocabulary (the runtime
    # sibling of the EVENT-DRIFT lint rule)
    names = {e[2] for e in rec.events()}
    assert names <= set(EVENT_FIELDS), names - set(EVENT_FIELDS)
    # the load-bearing decisions all made it into the log
    for must in ("submit", "admit", "dispatch", "fetch", "inject",
                 "fault", "rebuild", "replay", "health", "finish",
                 "bundle"):
        assert must in names or must == "bundle", must
    # seq strictly increasing
    seqs = [e[0] for e in rec.events()]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    # the bundle's event log names injections AND detections
    ev_names = {e["event"] for e in b["events.jsonl"]}
    assert {"inject", "fault", "rebuild"} <= ev_names
    # request records: every submitted request, in submit order, with
    # its replayable sampling params
    rows = b["requests.jsonl"]
    assert [r["request_id"] for r in rows] == [r.request_id
                                               for r in reqs]
    assert all(r["status"] in ("completed", "active", "queued")
               for r in rows)
    # fault plan round-trips with its firing record
    assert len(b["fault_plan.json"]["specs"]) == 3
    assert b["fault_plan.json"]["injected"]
    # config carries what replay needs
    assert b["config.json"]["engine"]["model"]["vocab_size"] == VOCAB
    assert b["config.json"]["scheduler"]["pipeline_depth"] == 2


def test_chaos_bundle_replay_bit_identical(chaos_bundle):
    bundle_path, _, sched, _, reqs = chaos_bundle
    # with the recorded fault plan re-armed: the incident replays, and
    # every stream still reproduces its recorded prefix exactly
    out = replay_bundle(bundle_path, verbose=False)
    assert out["mismatches"] == [], out["mismatches"]
    assert out["replayed"] == len(reqs) and not out["skipped"]
    # and clean (--no-faults): per-request determinism means streams
    # cannot depend on where faults landed — every COMPLETED request
    # must also match the live scheduler's final completions exactly
    out2 = replay_bundle(bundle_path, no_faults=True, verbose=False)
    assert out2["mismatches"] == [] and out2["faults_reinjected"] == 0
    assert out2["matched"] == out2["replayed"] == len(reqs)


def test_report_runs_with_jax_purged(chaos_bundle):
    """``--report`` must need NOTHING beyond the stdlib: render the
    incident timeline in a subprocess with jax/numpy/scipy purged from
    sys.modules and blocked from re-import."""
    bundle_path, _, _, _, _ = chaos_bundle
    code = f'''
import sys

BLOCKED = ("jax", "jaxlib", "numpy", "scipy", "torch", "tensorboard")


class _Blocker:
    def find_spec(self, name, path=None, target=None):
        if name.split(".")[0] in BLOCKED:
            raise ImportError(f"blocked by test: {{name}}")
        return None


for mod in list(sys.modules):
    if mod.split(".")[0] in BLOCKED:
        del sys.modules[mod]
sys.meta_path.insert(0, _Blocker())

from apex_tpu.telemetry.replay import main
rc = main(["{bundle_path}", "--report"])
assert rc == 0
assert not any(m.split(".")[0] in BLOCKED for m in sys.modules)
print("REPORT_DEP_FREE_OK")
'''
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr
    assert "REPORT_DEP_FREE_OK" in out.stdout
    # and in-process: the report names the cause, the timeline, and
    # every request
    text = render_report(read_bundle(bundle_path))
    assert "post-mortem bundle" in text and "timeline" in text
    assert "FAULT" in text and "b0" in text


def _soak(eng, bundle_dir):
    sched = Scheduler(
        eng, pipeline_depth=2, recorder=FlightRecorder(),
        bundle_dir=bundle_dir,
        bundle_meta={"params": {"init_seed": 0}},
        resilience=ResilienceConfig(backoff_base_s=0.001,
                                    max_retries=4))
    for r in _reqs(8):
        sched.submit(r)
    sched.run_until_idle()
    return sched


def test_recorder_keeps_recompile_guard_flat(chaos_bundle):
    """The black box must be trace-invisible: once a soak has compiled
    every program its tick sequence uses, an IDENTICAL soak — recorder
    on, bundle dumped mid-guard — must not compile anything. (A warm
    pass runs first so the armed rerun repeats a fully-compiled tick
    sequence; the engine never calls ``warmup()`` here, exactly like a
    service that lazily compiled its way to steady state.)"""
    bundle_path, eng, _, _, _ = chaos_bundle
    bundle_dir = os.path.dirname(bundle_path)
    eng.fault_plan.reset()
    warm = _soak(eng, bundle_dir)  # compiles anything the fixture missed
    eng.fault_plan.reset()
    with eng.recompile_guard():
        sched2 = _soak(eng, bundle_dir)
        sched2.dump_bundle("guard-flat-probe")
    # parity rides along: same trace, same (reset) plan — completions
    # must match the warm run's bit-for-bit
    for rid, comp in warm.completions.items():
        assert sched2.completions[rid].tokens == comp.tokens, rid


# --- /debug endpoints (host-only) -------------------------------------------


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.read().decode()


def test_debug_endpoints_and_vars(tmp_path):
    rec = FlightRecorder()
    for i in range(10):
        rec.record("finish", f"r{i}", "eos", i)
    dumped = []

    def trigger():
        p = str(tmp_path / f"t{len(dumped)}")
        write_bundle(p, {"manifest.json": {"cause": "http"}})
        dumped.append(p)
        return p

    server = MetricsServer(Registry(), recorder=rec,
                           bundle_trigger=trigger).start()
    try:
        status, body = _get(f"{server.url}/debug/events?n=3")
        assert status == 200
        evs = json.loads(body)
        assert [e["seq"] for e in evs] == [8, 9, 10]
        assert evs[0]["event"] == "finish" and evs[0]["reason"] == "eos"
        status, body = _get(f"{server.url}/vars")
        v = json.loads(body)
        assert v["flightrec"]["events_total"] == 10
        status, body = _get(f"{server.url}/debug/bundle")
        assert status == 200
        assert json.loads(body)["bundle"] == dumped[0]
        assert os.path.isdir(dumped[0])
    finally:
        server.stop()
    # no-recorder behavior unchanged: the endpoints 404 and /vars
    # carries no flightrec block
    server = MetricsServer(Registry()).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"{server.url}/debug/events")
        assert ei.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"{server.url}/debug/bundle")
        assert ei.value.code == 404
        _, body = _get(f"{server.url}/vars")
        assert "flightrec" not in json.loads(body)
    finally:
        server.stop()


def test_recorder_less_scheduler_clears_fault_observer(model):
    """The NEWEST scheduler owns ``FaultPlan.on_inject``: a
    recorder-less scheduler over a shared engine (the bench's on/off
    A/B, a service rebuilding on config reload) must clear a dead
    predecessor's wiring, or its injections keep landing in the old
    recorder's ring on the old scheduler's clock."""
    cfg, params, mesh = model
    eng = Engine(cfg, params, mesh,
                 EngineConfig(slots=1, max_prompt_len=8,
                              max_seq_len=16),
                 fault_plan=FaultPlan.random(1, 1))
    Scheduler(eng, recorder=FlightRecorder())
    assert eng.fault_plan.on_inject is not None
    Scheduler(eng)
    assert eng.fault_plan.on_inject is None


# --- Engine.close() idempotence (the double-release regression) -------------


def test_engine_close_idempotent_and_reentrant(model, tmp_path):
    cfg, params, mesh = model
    eng = Engine(cfg, params, mesh,
                 EngineConfig(slots=1, max_prompt_len=8,
                              max_seq_len=16))
    sched = Scheduler(eng, bundle_dir=str(tmp_path),
                      recorder=FlightRecorder())
    sent1 = eng.recompile_sentinel()
    # a bundle-triggered dump reads engine state (describe, compiled
    # cache sizes, the sentinel snapshot) — it must never re-install
    # or consume the listener
    sched.dump_bundle("before-close")
    eng.close()
    eng.close()  # idempotent: second close is a no-op, not an error
    assert eng._sentinel is None
    sent1.uninstall()  # and a direct re-uninstall is harmless too
    # dumping after close still works (manifest simply drops the
    # sentinel block), and closing again after THAT dump is fine
    p = sched.dump_bundle("after-close")
    assert "recompile" not in read_bundle(p)["manifest.json"]
    eng.close()
    # the releases above must not have detached anyone else's
    # listener: a fresh sentinel still observes compiles
    eng2 = Engine(cfg, params, mesh,
                  EngineConfig(slots=1, max_prompt_len=8,
                               max_seq_len=16))
    sent2 = eng2.recompile_sentinel()
    if sent2.monitoring_available:
        before = sent2.compiles_total()["backend_compiles"]
        jax.jit(lambda x: x * 3 + 1)(jax.numpy.ones((4,)))
        assert sent2.compiles_total()["backend_compiles"] > before
    eng2.close()
