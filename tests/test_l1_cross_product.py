"""L1 integration: precision-mode × loss-scaling × data-parallel cross
product (SURVEY.md §4: tests/L1/common main_amp.py + compare.py (U)).

The reference trains an imagenet-ish model under every (opt-level,
loss-scale, DDP) combination and diffs end-of-run losses/weights against
saved references. Here the oracle is in-process: fp32 single-device
training is the reference run; every other combination must track it
(same seed, same data) within mode-appropriate tolerance, and DP on/off
must agree exactly for the same effective batch.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import mesh as mx
from apex_tpu.amp import ScalerConfig
from apex_tpu.models import gpt, training
from apex_tpu.optimizers import fused_adam, fused_sgd

CFG = dict(vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
           seq_len=16, remat=False)
STEPS = 6


def _data():
    tok = jax.random.randint(jax.random.PRNGKey(7), (8, 16), 0, 64)
    return tok, jnp.roll(tok, -1, 1)


def _train(compute_dtype, scaler_cfg, n_devices, opt=None, steps=STEPS):
    cfg = gpt.GPTConfig(compute_dtype=compute_dtype, **CFG)
    mesh = mx.build_mesh(tp=1, devices=jax.devices()[:n_devices])
    init_fn, step_fn = training.make_train_step(
        cfg, mesh, opt or fused_adam(5e-3), scaler_cfg)
    state = init_fn(jax.random.PRNGKey(0))
    tok, tgt = _data()
    losses = []
    for _ in range(steps):
        state, m = step_fn(state, tok, tgt)
        losses.append(float(m["loss"]))
    return np.asarray(losses), state


@pytest.fixture(scope="module")
def reference():
    """O0: fp32, no scaling, single device."""
    return _train(jnp.float32, ScalerConfig(enabled=False), 1)


def test_o0_converges(reference):
    losses, _ = reference
    assert losses[-1] < losses[0]


def test_bf16_tracks_fp32(reference):
    """bf16 compute (the TPU O1/O2 analogue, no scaler needed)."""
    ref_losses, _ = reference
    losses, _ = _train(jnp.bfloat16, ScalerConfig(enabled=False), 1)
    np.testing.assert_allclose(losses, ref_losses, rtol=0.08)


def test_fp16_dynamic_scaling_tracks_fp32(reference):
    """fp16 + dynamic loss scaler (apex O2 parity mode)."""
    ref_losses, _ = reference
    losses, state = _train(jnp.float16, ScalerConfig(), 1)
    np.testing.assert_allclose(losses, ref_losses, rtol=0.08)
    assert float(state.scaler.loss_scale) > 0


def test_dp_matches_single_device(reference):
    """DDP on/off with identical effective batch: same loss curve (the
    cross_product_distributed leg (U)). Params agree to reduction-order
    tolerance — pmean-of-shard-grads reassociates the batch sum, and Adam
    amplifies ulp-level drift on near-zero moments."""
    ref_losses, ref_state = reference
    losses, state = _train(jnp.float32, ScalerConfig(enabled=False), 8)
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(ref_state.params),
                    jax.tree.leaves(state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-2, atol=1e-4)


def test_sgd_cross(reference):
    """Second optimizer leg of the cross product."""
    losses, _ = _train(jnp.float32, ScalerConfig(enabled=False), 1,
                       opt=fused_sgd(0.1, momentum=0.9))
    assert losses[-1] < losses[0]
