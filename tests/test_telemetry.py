"""apex_tpu.telemetry — registry / spans / recompile sentinel / http.

Headline (the engine-invariant acceptance): drive the serving Engine
through warmup, arm ``RecompileGuard``, run admit / decode-chunk /
retire across varied slots and sampling params, and assert
``compiles_total`` stays flat — then prove a deliberately shape-busting
call trips the guard. Plus: exposition round trips through a minimal
Prometheus parser scraped from a LIVE engine, the span timeline exports
as valid Chrome-trace JSON, and the whole layer imports with
torch/tensorboard purged (dependency-free by contract).
"""

import json
import subprocess
import sys
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import mesh as mx
from apex_tpu.models import gpt
from apex_tpu.serving import Request, SamplingParams
from apex_tpu.serving.engine import Engine, EngineConfig
from apex_tpu.serving.request import FINISH_REASONS
from apex_tpu.serving.scheduler import Scheduler
from apex_tpu.telemetry import (
    MetricsServer,
    RecompileError,
    Registry,
    Ring,
    SpanRecorder,
    parse_prometheus_text,
)
from apex_tpu.telemetry import recompile as rc
from apex_tpu.telemetry import spans as spans_mod
from apex_tpu.transformer.testing import standalone_gpt_config

VOCAB = 96


# --- ring ------------------------------------------------------------------


def test_ring_wraparound_and_order():
    r = Ring(3)
    assert len(r) == 0 and r.values() == [] and r.total == 0
    for i in range(5):
        r.append(i)
    assert len(r) == 3 and r.total == 5 and r.dropped == 2
    assert r.values() == [2, 3, 4]  # oldest first across the wrap
    # array() is for order-insensitive stats: same multiset, any order
    assert sorted(r.array()) == [2.0, 3.0, 4.0]
    r.clear()
    assert len(r) == 0 and r.total == 0
    with pytest.raises(ValueError):
        Ring(0)


# --- registry --------------------------------------------------------------


def test_registry_counter_gauge_labels():
    reg = Registry()
    c = reg.counter("requests_total", "all requests")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError, match="only go up"):
        c.inc(-1)
    g = reg.gauge("depth")
    g.set(4)
    g.inc()
    g.dec(2)
    assert g.value == 3.0
    lab = reg.counter("finished_total", labels=("reason",))
    lab.labels(reason="eos").inc()
    lab.labels(reason="eos").inc()
    lab.labels(reason="length").inc()
    assert lab.labels(reason="eos").value == 2.0
    with pytest.raises(ValueError, match="expected labels"):
        lab.labels(cause="eos")
    with pytest.raises(ValueError, match="declares labels"):
        lab.inc()
    # create-or-get is idempotent; a conflicting re-registration raises
    assert reg.counter("requests_total") is c
    with pytest.raises(ValueError, match="re-registered"):
        reg.gauge("requests_total")
    with pytest.raises(ValueError, match="invalid metric name"):
        reg.counter("bad name")


def test_registry_histogram_and_prom_roundtrip():
    reg = Registry()
    h = reg.histogram("ttft_seconds", "ttft", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.05, 5.0):
        h.observe(v)
    reg.gauge("depth", "queue depth").set(2)
    reg.counter("finished_total", labels=("reason",)).labels(
        reason='we"ird\\').inc()
    # literal backslash followed by 'n' — the escape-adjacency trap
    reg.counter("paths_total", labels=("path",)).labels(
        path="C:\\new\nline").inc()
    text = reg.to_prometheus_text()
    parsed = parse_prometheus_text(text)
    assert parsed["ttft_seconds_bucket"][(("le", "0.01"),)] == 1.0
    assert parsed["ttft_seconds_bucket"][(("le", "0.1"),)] == 3.0
    assert parsed["ttft_seconds_bucket"][(("le", "1"),)] == 3.0
    assert parsed["ttft_seconds_bucket"][(("le", "+Inf"),)] == 4.0
    assert parsed["ttft_seconds_count"][()] == 4.0
    assert parsed["ttft_seconds_sum"][()] == pytest.approx(5.105)
    assert parsed["depth"][()] == 2.0
    # label-value escaping survives the round trip
    assert parsed["finished_total"][(("reason", 'we"ird\\'),)] == 1.0
    assert parsed["paths_total"][(("path", "C:\\new\nline"),)] == 1.0
    # JSON snapshot agrees
    d = reg.to_dict()
    json.dumps(d)  # must be JSON-ready
    assert d["ttft_seconds"]["samples"][0]["count"] == 4
    assert d["ttft_seconds"]["samples"][0]["buckets"]["+Inf"] == 4
    with pytest.raises(ValueError, match="sorted"):
        reg.histogram("bad_seconds", buckets=(1.0, 0.1))


# --- spans -----------------------------------------------------------------


def test_span_recorder_chrome_trace():
    t = [0.0]
    rec = SpanRecorder(capacity=64, clock=lambda: t[0])
    rec.mark("r0", spans_mod.PHASE_QUEUED)
    t[0] = 0.010
    rec.mark("r0", spans_mod.PHASE_PREFILL, note="slot 0")
    t[0] = 0.025
    rec.mark("r0", spans_mod.PHASE_FIRST_TOKEN)
    with rec.section("engine.step"):
        t[0] = 0.040
    rec.mark("r0", spans_mod.PHASE_DECODE)
    rec.mark("r1", spans_mod.PHASE_QUEUED)
    t[0] = 0.050
    rec.mark("r0", spans_mod.PHASE_RETIRED, note="eos")
    ct = rec.to_chrome_trace()
    json.dumps(ct)  # valid Chrome-trace JSON
    evs = ct["traceEvents"]
    xs = {(e["name"], e["ts"], e["dur"]) for e in evs if e["ph"] == "X"}
    # consecutive marks become complete events named by the open phase
    assert ("queued", 0.0, 10000.0) in xs
    assert ("prefill", 10000.0, 15000.0) in xs
    assert ("engine.step", 25000.0, 15000.0) in xs
    # distinct requests get distinct lanes
    lanes = {e["tid"] for e in evs
             if e["ph"] == "X" and e["pid"] == 1}
    r1_lane = [e["tid"] for e in evs if e["ph"] == "M"
               and e.get("args", {}).get("name") == "req r1"]
    assert r1_lane and r1_lane[0] not in lanes
    # terminal marks are instants
    instants = {e["name"] for e in evs if e["ph"] == "i"}
    assert "retired" in instants and "queued" in instants
    s = rec.summary()
    assert s == {"events": 7, "events_total": 7, "events_dropped": 0,
                 "requests": 2}


def test_span_recorder_bounded():
    rec = SpanRecorder(capacity=4, clock=lambda: 0.0)
    for i in range(10):
        rec.mark(f"r{i}", "queued")
    s = rec.summary()
    assert s["events"] == 4 and s["events_dropped"] == 6
    json.dumps(rec.to_chrome_trace())


# --- recompile sentinel ----------------------------------------------------


def test_recompile_sentinel_counts_and_guard_trip():
    reg = Registry()
    sent = rc.RecompileSentinel(registry=reg).install()
    try:
        if not sent.monitoring_available:
            pytest.skip("runtime has no jax.monitoring")
        f = jax.jit(lambda x: x * 3 + 1)
        before = sent.compiles_total()
        f(jnp.ones((4,)))  # first call: an executable materialises
        after = sent.compiles_total()
        assert after["backend_compiles"] > before["backend_compiles"]
        sent.track("f", f)
        # steady state: repeat calls are in-memory cache hits — silent
        with sent.guard() as g:
            f(jnp.ones((4,)))
            assert g.check() == {} and not g.tripped
        # a new shape recompiles: alarm + raise, attributed to "f"
        with pytest.raises(RecompileError, match="trace-stability"):
            with sent.guard() as g:
                f(jnp.ones((9,)))
        assert g.alarms and g.tripped
        assert g.delta().get("tracked", {}).get("f") == 1
        assert reg.counter("recompile_alarms_total").value >= 1
        assert reg.counter("jax_compiles_total").value >= 2
        # raise_on_recompile=False: report, don't raise
        with sent.guard(raise_on_recompile=False) as g:
            f(jnp.ones((17,)))
        assert g.tripped and g.check()["backend_compiles"] >= 1
        # concurrent guards: one compile = ONE observed breach on the
        # shared alarm counter (each guard still records it locally)
        alarms_before = reg.counter("recompile_alarms_total").value
        with sent.guard(raise_on_recompile=False) as g1:
            with sent.guard(raise_on_recompile=False) as g2:
                f(jnp.ones((23,)))
        # every armed guard saw the same events; the shared counter
        # advanced once per EVENT, not once per (event, guard) pair
        # (note one host call can legitimately fire several compile
        # events — e.g. jnp.ones of a fresh shape compiles its own
        # fill program before f does)
        assert g1.alarms and len(g1.alarms) == len(g2.alarms)
        assert reg.counter("recompile_alarms_total").value == \
            alarms_before + len(g1.alarms)
    finally:
        sent.uninstall()


def test_sentinel_uninstall_releases_listener():
    """install/uninstall is listener-neutral — engines created in a
    loop must not grow jax.monitoring's listener list (uninstall used
    to silently no-op: the private unregister helpers live on
    jax._src.monitoring, not the public re-export). All live sentinels
    now share ONE refcounted hub listener: a second sentinel adds no
    registration, and the LAST uninstall releases the one there is —
    pinned here so N engine replicas hold exactly one listener."""
    try:
        from jax._src import monitoring as impl
    except ImportError:
        pytest.skip("no jax._src.monitoring")
    get = getattr(impl, "get_event_duration_listeners", None)
    if get is None:
        pytest.skip("runtime lacks listener introspection")
    n0 = len(get())
    sent = rc.RecompileSentinel().install()
    if not sent.monitoring_available:
        pytest.skip("runtime has no jax.monitoring")
    assert len(get()) == n0 + 1
    sent.install()  # idempotent: no second registration
    assert len(get()) == n0 + 1
    # a SECOND sentinel shares the hub's one listener (refcount), and
    # releasing either order leaves the other's delivery intact
    sent2 = rc.RecompileSentinel().install()
    assert len(get()) == n0 + 1
    sent.uninstall()
    assert len(get()) == n0 + 1  # sent2 still holds the hub
    sent2.uninstall()
    assert len(get()) == n0
    sent.uninstall()  # idempotent


def test_recompile_guard_cache_poll_fallback(monkeypatch):
    """Legacy runtimes without jax.monitoring: the sentinel degrades to
    tracked-function jit-cache polling and the guard still trips."""
    from apex_tpu import _compat

    monkeypatch.setattr(_compat, "register_monitoring_listeners",
                        lambda *a: None)
    reg = Registry()
    sent = rc.RecompileSentinel(registry=reg).install()
    assert not sent.monitoring_available
    f = jax.jit(lambda x: x - 2)
    f(jnp.ones((3,)))
    sent.track("f", f)
    with sent.guard() as g:
        f(jnp.ones((3,)))
        assert g.check() == {}
    with pytest.raises(RecompileError, match="tracked"):
        with sent.guard():
            f(jnp.ones((6,)))
    # the breach is visible on the alarm counter even though no event
    # listener exists — cache-poll detection feeds the same metric
    assert reg.counter("recompile_alarms_total").value == 1.0
    # ...and with raise_on_recompile=False the exit check still records
    with sent.guard(raise_on_recompile=False) as g:
        f(jnp.ones((9,)))
    assert g.tripped and g.alarms
    assert reg.counter("recompile_alarms_total").value == 2.0
    assert sent.compiles_total()["backend_compiles"] == 0  # no listener
    sent.uninstall()  # no-op, must not raise


# --- the engine acceptance: warmup → guard → flat --------------------------


def _cfg(**overrides):
    base = dict(vocab_size=VOCAB, seq_len=64)
    base.update(overrides)
    return standalone_gpt_config(**base)


def _varied_requests(n, *, seed0, eos=None):
    """Greedy and sampled lanes, varied prompt lengths / budgets /
    temperatures / top-k / top-p — the admission-diversity sweep.
    Prompt lengths span 1..10, so admissions land in BOTH prefill
    buckets of the mpl=10 fixture engine (8 and 10)."""
    reqs = []
    for i in range(n):
        p_len = 1 + (7 * i + 2) % 10
        prompt = [int(t) for t in jax.random.randint(
            jax.random.PRNGKey(seed0 + i), (p_len,), 0, VOCAB)]
        if i % 2:
            sp = SamplingParams(temperature=0.7 + 0.2 * (i % 3),
                                top_k=(0, 5, 9)[i % 3],
                                top_p=(1.0, 0.9, 0.85)[i % 3],
                                seed=seed0 + i)
        else:
            sp = SamplingParams()
        reqs.append(Request(f"q{seed0}_{i}", prompt,
                            max_tokens=3 + i % 5, sampling=sp,
                            eos_token_id=eos))
    return reqs


@pytest.fixture(scope="module")
def served_engine(devices8):
    """One warmed engine (chunked decode, two prefill buckets, two
    admission batch sizes) + its recompile sentinel, shared by the
    guard and live-scrape tests. ``Engine.warmup()`` replaces the old
    hand-rolled scheduler warm run — it compiles every program
    (init/step/retire + all four (bucket, k) admission variants) plus
    the seeded-admission host path."""
    cfg = _cfg()
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=devices8[:1])
    eng = Engine(cfg, params, mesh,
                 EngineConfig(slots=2, max_prompt_len=10, max_seq_len=24,
                              decode_chunk=8))
    registry = Registry()
    eng.recompile_sentinel(registry=registry)
    eng.warmup()  # apex: noqa[TIER1-COST]: shared warmed engine for the live /metrics e2e scrapes; warm-cache ~s
    yield cfg, params, mesh, eng, registry
    eng.close()  # release the process-wide monitoring listener


def test_engine_recompile_guard_stays_flat(served_engine):
    """The acceptance pin: after ``Engine.warmup()``, a full serve
    cycle — admissions through EVERY prefill bucket and admission batch
    size, pipelined chunked decode, deadline retire, varied sampling
    params — runs inside an armed RecompileGuard without a single
    compilation; a shape-busting call trips the same guard."""
    cfg, params, mesh, eng, registry = served_engine
    sent = eng.recompile_sentinel()
    sizes0 = eng.compiled_cache_sizes()
    assert set(sizes0.values()) == {1}, sizes0  # warmup compiled ALL
    now = [0.0]
    # build the request sets OUTSIDE the guard: their jax.random prompt
    # synthesis compiles for fresh prompt lengths, which is exactly the
    # kind of host-side compile the guard exists to catch. Four phases
    # steer admissions through every (bucket, k) variant: a short pair
    # (k=2, bucket 8), a pair with one long prompt (k=2, bucket 10),
    # then staggered singles long and short (k=1 at both buckets).
    def _mk(rid, p_len, i):
        prompt = [int(t) for t in jax.random.randint(
            jax.random.PRNGKey(3000 + i), (p_len,), 0, VOCAB)]
        sp = (SamplingParams(temperature=0.8 + 0.1 * (i % 3),
                             top_k=(0, 5, 9)[i % 3], seed=3000 + i)
              if i % 2 else SamplingParams())
        return Request(rid, prompt, max_tokens=3 + i % 4, sampling=sp,
                       eos_token_id=13)

    phases = [[_mk("ga", 3, 0), _mk("gb", 8, 1)],     # k=2, bucket 8
              [_mk("gc", 10, 2), _mk("gd", 5, 3)],    # k=2, bucket 10
              [_mk("ge", 9, 4)],                      # k=1, bucket 10
              [_mk("gf", 2, 5)]]                      # k=1, bucket 8
    with eng.recompile_guard() as g:
        sched = Scheduler(eng, clock=lambda: now[0], pipeline_depth=2)
        seen = set()
        for phase in phases:
            for r in phase:
                sched.submit(r)
            sched.step()
            now[0] += 1.0
            # deadline-retire one live slot mid-flight (a chunk is in
            # flight at depth 2), then drain the phase
            if len(seen) == 0 and sched.active:
                slot = next(iter(sched.active))
                sched.active[slot].request.deadline = now[0] - 0.5
            sched.run_until_idle()
            seen |= set(sched.completions)
        assert len(sched.completions) == 6
        assert g.check() == {}  # flat mid-flight, by construction
    assert not g.tripped
    # compiles_total flat: per-program jit caches did not grow
    totals = sent.compiles_total()
    # the step program tracks per decode-chunk variant (step_c{chunk}
    # — the self-tuning ladder's naming; a single-rung engine has one)
    assert totals["tracked"] == {
        "init": 1, "step_c8": 1, "retire": 1,
        "admit_p8_k1": 1, "admit_p8_k2": 1,
        "admit_p10_k1": 1, "admit_p10_k2": 1}
    assert eng.compiled_cache_sizes() == sizes0
    if not sent.monitoring_available:
        pytest.skip("no jax.monitoring: event-trip half needs it")
    # the same guard trips on a deliberately shape-busting call
    with pytest.raises(RecompileError, match="RecompileGuard"):
        with eng.recompile_guard():
            jax.jit(lambda x: x * 2.0)(np.arange(7.0))
    assert registry.counter("recompile_alarms_total").value >= 1
    # re-passing the ALREADY-WIRED registry is fine (the natural
    # re-arm pattern)...
    assert eng.recompile_sentinel(registry=registry) is sent
    # ...but wiring a DIFFERENT registry after the fact is a loud
    # error, not silently-absent metrics
    with pytest.raises(ValueError, match="FIRST"):
        eng.recompile_sentinel(registry=Registry())


# --- live /metrics endpoint over a serving engine --------------------------


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, resp.read().decode("utf-8")


def test_metrics_endpoint_live_engine(served_engine):
    """End-to-end smoke over the PIPELINED loop: scrape /metrics from a
    LIVE engine mid-batch (with a decode chunk in flight), round-trip
    the text through the minimal parser, assert the admission-batch /
    bucket / in-flight instrumentation is present and consistent with
    the scheduler's own summary, check /healthz and /vars, and validate
    the span export as Chrome-trace JSON."""
    cfg, params, mesh, eng, _ = served_engine
    registry = Registry()
    spans = SpanRecorder()
    sched = Scheduler(eng, registry=registry, spans=spans,
                      pipeline_depth=2)
    server = MetricsServer(registry, spans=spans,
                           sentinel=eng.recompile_sentinel()).start()
    try:
        # budgets of 12 outlive a decode_chunk=8 dispatch, so slots are
        # observably live at the mid-flight scrape
        for r in _varied_requests(4, seed0=4000):
            sched.submit(Request(r.request_id, r.prompt, max_tokens=12,
                                 sampling=r.sampling))
        sched.step()  # both slots admitted + one chunk; 2 still queued
        status, mid = _get(server.url + "/metrics")
        assert status == 200
        p = parse_prometheus_text(mid)
        assert p["serving_active_slots"][()] >= 1.0
        assert p["serving_requests_admitted_total"][()] >= 2.0
        assert p["serving_slots_total"][()] == 2.0
        # at depth 2 the first tick's chunk is still in flight when the
        # tick returns — the pipeline gauge shows it
        assert p["serving_inflight_chunks"][()] == 1.0
        sched.run_until_idle()
        _, done = _get(server.url + "/metrics")
        p = parse_prometheus_text(done)
        by_reason = {dict(k)["reason"]: v for k, v in
                     p["serving_requests_finished_total"].items()}
        assert set(by_reason) == set(FINISH_REASONS)  # zeros present
        assert sum(by_reason.values()) == 4.0
        assert p["serving_queue_depth"][()] == 0.0
        assert p["serving_inflight_chunks"][()] == 0.0  # drained
        assert p["serving_ttft_seconds_count"][()] == 4.0
        assert p["serving_token_latency_seconds_count"][()] == \
            p["serving_tokens_emitted_total"][()] - 4.0
        # admission instrumentation is consistent with the scheduler's
        # own summary: every admitted request is counted exactly once
        # by batch size and once by bucket, and the dispatch counter
        # matches the summary's amortisation number
        s = sched.summary()
        admitted = p["serving_requests_admitted_total"][()]
        assert admitted == s["admitted_requests"] == 4.0
        by_size = {dict(k)["size"]: v for k, v in
                   p["serving_admit_batch_requests_total"].items()}
        assert set(by_size) == {str(k) for k in eng.admit_batch_sizes}
        assert sum(by_size.values()) == admitted
        by_bucket = {dict(k)["bucket"]: v for k, v in
                     p["serving_prefill_bucket_requests_total"].items()}
        assert set(by_bucket) == {str(b) for b in eng.prompt_buckets}
        assert sum(by_bucket.values()) == admitted
        assert p["serving_admit_dispatches_total"][()] == \
            s["admit_dispatches"] > 0
        assert p["serving_tokens_emitted_total"][()] == \
            s["tokens_emitted"]
        status, health = _get(server.url + "/healthz")
        assert status == 200 and health == "ok\n"
        status, vars_body = _get(server.url + "/vars")
        v = json.loads(vars_body)
        assert v["spans"]["requests"] == 4
        assert v["recompile"]["tracked"]["step_c8"] == 1
        assert v["metrics"]["serving_tokens_emitted_total"][
            "samples"][0]["value"] >= 4.0
        status, _ = _get(server.url + "/metrics?from=test")
        assert status == 200
        with pytest.raises(urllib.error.HTTPError):
            _get(server.url + "/nope")
    finally:
        server.stop()
    # span export: valid Chrome trace with the full phase vocabulary,
    # including the pipelined loop's dispatch-vs-fetch section split
    ct = spans.to_chrome_trace()
    json.loads(json.dumps(ct))
    names = {e["name"] for e in ct["traceEvents"]
             if e["ph"] in ("X", "i")}
    assert {"queued", "prefill", "first_token", "decode", "retired",
            "engine.dispatch", "engine.fetch", "engine.admit"} <= names
    for e in ct["traceEvents"]:
        if e["ph"] == "X":
            assert e["ts"] >= 0.0 and e["dur"] >= 0.0


# --- dependency-free contract ----------------------------------------------


def test_telemetry_imports_without_torch_tensorboard(tmp_path):
    """The layer must import with torch/tensorboard purged AND blocked
    — run in a subprocess with an import hook that fails either import,
    proving no telemetry module (or its transitive imports) touches
    them."""
    code = """
import sys

BLOCKED = ("torch", "tensorboard")


class _Blocker:
    def find_spec(self, name, path=None, target=None):
        if name.split(".")[0] in BLOCKED:
            raise ImportError(f"blocked by test: {name}")
        return None


for mod in list(sys.modules):
    if mod.split(".")[0] in BLOCKED:
        del sys.modules[mod]
sys.meta_path.insert(0, _Blocker())

import apex_tpu.telemetry as t
import apex_tpu.telemetry.ring
import apex_tpu.telemetry.registry
import apex_tpu.telemetry.spans
import apex_tpu.telemetry.http
import apex_tpu.telemetry.recompile
import apex_tpu.telemetry.flightrec
import apex_tpu.telemetry.replay

r = t.Registry()
r.counter("x_total").inc()
assert "x_total 1" in r.to_prometheus_text()
assert not any(m.split(".")[0] in BLOCKED for m in sys.modules)
print("DEP_FREE_OK")
"""
    import os

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "DEP_FREE_OK" in out.stdout
